"""Resilience suite — chaos harness, crash-consistent checkpoints,
dispatch watchdog, campaign supervisor (killerbeez_tpu/resilience/).

The invariants pinned here are ISSUE 8's acceptance criteria:

  * no finding is ever lost after admission (SIGKILL at randomized
    persistence points + resume ends with the fault-free control
    run's exact findings/corpus sets);
  * the event seq never regresses (across rotation, kills, resumes,
    and a torn/lost log healed from the checkpoint high-water);
  * no duplicate corpus arms after kill/resume cycles;
  * a supervised campaign survives an injected device loss AND a
    SIGKILL and converges to the control run's state;
  * the watchdog kills a synthetically-hung dispatch within 2x the
    armed deadline.

CLI-level cases run the fuzzer in a SUBPROCESS (SIGKILL faults must
not kill pytest); the CI chaos lane runs this whole file.
"""

import errno
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from killerbeez_tpu.corpus.store import CorpusStore
from killerbeez_tpu.resilience import (
    DEVICE_LOST_EXIT_CODE, WATCHDOG_EXIT_CODE, chaos_point,
    is_device_loss,
)
from killerbeez_tpu.resilience import chaos as chaos_mod
from killerbeez_tpu.resilience import checkpoint as ckpt
from killerbeez_tpu.resilience.supervisor import (
    CLEAN, CRASH, DEVICE_LOST, Supervisor, WATCHDOG, classify_exit,
    shrink_mesh,
)
from killerbeez_tpu.resilience.watchdog import DispatchWatchdog

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _chaos_off():
    """Never leak a configured chaos engine between tests."""
    yield
    chaos_mod.configure(None)


# ---------------------------------------------------------------------------
# chaos engine
# ---------------------------------------------------------------------------

def test_chaos_off_is_noop():
    chaos_mod.configure(None)
    chaos_point("device_dispatch")      # nothing configured: no-op
    chaos_point("persist", path="/nope", data=b"x")


def test_chaos_hit_trigger_fires_exactly_once():
    chaos_mod.configure({"faults": [
        {"point": "device_dispatch", "mode": "raise", "hit": 3}]})
    chaos_point("device_dispatch")
    chaos_point("device_dispatch")
    with pytest.raises(chaos_mod.XlaRuntimeError) as ei:
        chaos_point("device_dispatch")
    assert is_device_loss(ei.value)     # classified like the real one
    chaos_point("device_dispatch")      # hit 4: armed once, not again


def test_chaos_every_trigger_and_counters():
    eng = chaos_mod.configure({"faults": [
        {"point": "manager_rpc", "mode": "enospc", "every": 2}]})
    chaos_point("manager_rpc")
    with pytest.raises(OSError):
        chaos_point("manager_rpc")
    chaos_point("manager_rpc")
    with pytest.raises(OSError):
        chaos_point("manager_rpc")
    assert eng.state()["hits"]["manager_rpc"] == 4
    assert eng.state()["fired"]["manager_rpc/enospc"] == 2


def test_chaos_prob_trigger_is_seed_deterministic():
    def fire_pattern(seed):
        eng = chaos_mod.configure({"seed": seed, "faults": [
            {"point": "p", "mode": "enospc", "prob": 0.5}]})
        pat = []
        for _ in range(32):
            try:
                chaos_point("p")
                pat.append(0)
            except OSError:
                pat.append(1)
        return pat, eng
    a, _ = fire_pattern(7)
    b, _ = fire_pattern(7)
    c, _ = fire_pattern(8)
    assert a == b                       # same seed: same fault train
    assert a != c
    assert 0 < sum(a) < 32


def test_chaos_spec_from_json_string_and_file(tmp_path):
    eng = chaos_mod.configure(
        '{"faults": [{"point": "x", "mode": "timeout"}]}')
    assert eng.faults[0].mode == "timeout"
    f = tmp_path / "spec.json"
    f.write_text('{"faults": [{"point": "y", "mode": "http500"}]}')
    eng = chaos_mod.configure(f"@{f}")
    assert eng.faults[0].point == "y"
    with pytest.raises(ValueError):
        chaos_mod.configure({"faults": [{"point": "z",
                                         "mode": "nonsense"}]})


def test_chaos_http_modes_raise_urllib_errors():
    import urllib.error
    chaos_mod.configure({"faults": [
        {"point": "rpc", "mode": "http500", "hit": 1},
        {"point": "rpc", "mode": "timeout", "hit": 2}]})
    with pytest.raises(urllib.error.HTTPError):
        chaos_point("rpc", url="http://x")
    with pytest.raises(urllib.error.URLError):
        chaos_point("rpc", url="http://x")


def test_chaos_torn_write_tears_in_place_and_store_survives(tmp_path):
    """The ``torn`` mode bypasses temp+rename and leaves half the
    payload at the FINAL path: every loader must degrade, none may
    raise."""
    store = CorpusStore(str(tmp_path))
    store.save_state({"version": 1, "counters": {"execs": 1}})
    chaos_mod.configure({"faults": [
        {"point": "persist", "mode": "torn", "hit": 1}]})
    store.save_state({"version": 1, "counters": {"execs": 2}})
    chaos_mod.configure(None)
    raw = (tmp_path / "campaign.json").read_text()
    with pytest.raises(ValueError):
        json.loads(raw)                 # really torn on disk
    assert store.load_state() is None   # degrades, no raise
    assert store.load() == []


def test_chaos_enospc_on_persist_never_kills_the_store(tmp_path):
    store = CorpusStore(str(tmp_path))
    chaos_mod.configure({"faults": [
        {"point": "persist", "mode": "enospc", "every": 1}]})
    from killerbeez_tpu.corpus.store import CorpusEntry
    assert store.put(CorpusEntry(b"abc")) is False  # warned, survived
    store.save_state({"v": 1})
    assert store.save_checkpoint({"campaign": {"v": 1}}) is None


# ---------------------------------------------------------------------------
# exit classification / mesh degradation
# ---------------------------------------------------------------------------

def test_is_device_loss_markers():
    assert is_device_loss(RuntimeError("DEVICE_LOST: slice gone"))
    assert is_device_loss("XlaRuntimeError: INTERNAL")
    assert is_device_loss("TPU worker preempted")
    assert not is_device_loss(ValueError("bad option"))
    assert not is_device_loss("assertion failed")


def test_classify_exit():
    assert classify_exit(0, []) == CLEAN
    assert classify_exit(WATCHDOG_EXIT_CODE, []) == WATCHDOG
    assert classify_exit(DEVICE_LOST_EXIT_CODE, []) == DEVICE_LOST
    assert classify_exit(1, ["XlaRuntimeError: DEVICE_LOST"]) \
        == DEVICE_LOST
    assert classify_exit(1, ["ValueError: x"]) == CRASH
    assert classify_exit(-signal.SIGKILL, []) == CRASH


def test_shrink_mesh():
    assert shrink_mesh("4,2", 8) == "4,2"       # fits: unchanged
    assert shrink_mesh("4,2", 4) == "2,2"       # dp halves
    assert shrink_mesh("4,2", 2) == "1,2"
    assert shrink_mesh("4,2", 1) is None        # mp won't fit at all
    assert shrink_mesh("bogus", 8) is None


# ---------------------------------------------------------------------------
# crash-consistent checkpoint
# ---------------------------------------------------------------------------

def test_checkpoint_epoch_monotone_roundtrip(tmp_path):
    root = str(tmp_path)
    assert ckpt.load(root) is None
    e1 = ckpt.save(root, {"campaign": {"execs": 1}})
    e2 = ckpt.save(root, {"campaign": {"execs": 2}})
    assert (e1, e2) == (1, 2)
    doc = ckpt.load(root)
    assert doc["epoch"] == 2 and doc["campaign"]["execs"] == 2


def test_checkpoint_torn_live_file_heals_from_prev(tmp_path):
    """Torn-tail healing pinned: garbage over the live checkpoint
    (chaos ``torn``, fs corruption) falls back to the previous
    epoch instead of losing the campaign."""
    root = str(tmp_path)
    ckpt.save(root, {"campaign": {"execs": 1}})
    ckpt.save(root, {"campaign": {"execs": 2}})
    live = tmp_path / ckpt.CHECKPOINT_FILE
    live.write_text('{"epoch": 3, "campaign": {"ex')   # torn mid-write
    doc = ckpt.load(root)
    assert doc["epoch"] == 1            # .prev holds the epoch before
    assert doc["campaign"]["execs"] == 1
    # the next save continues the epoch line past the healed doc
    assert ckpt.save(root, {"campaign": {"execs": 3}}) == 2


def test_checkpoint_sections_carry_forward(tmp_path):
    """An interval persist without a cracker must not drop the solver
    section a previous epoch recorded."""
    store = CorpusStore(str(tmp_path))
    store.save_checkpoint({"campaign": {"a": 1},
                           "solver": {"0:1": {"status": "solved"}},
                           "event_seq": 9})
    store.save_checkpoint({"campaign": {"a": 2}})
    ck = store.load_checkpoint()
    assert ck["campaign"] == {"a": 2}
    assert ck["solver"] == {"0:1": {"status": "solved"}}
    assert ck["event_seq"] == 9


def test_checkpoint_torn_live_never_destroys_prev_on_next_save(
        tmp_path):
    """A torn live file must NOT be hardlinked over ``.prev`` by the
    next save: with the old behavior, a write failure (or a kill)
    after that link left NO readable checkpoint at all."""
    root = str(tmp_path)
    ckpt.save(root, {"campaign": {"execs": 1}})
    ckpt.save(root, {"campaign": {"execs": 2}})
    (tmp_path / ckpt.CHECKPOINT_FILE).write_text('{"epoch": 3, "ca')
    chaos_mod.configure({"faults": [
        {"point": "persist", "mode": "enospc", "hit": 1}]})
    store = CorpusStore(root)
    assert store.save_checkpoint({"campaign": {"execs": 3}}) is None
    chaos_mod.configure(None)
    doc = ckpt.load(root)               # .prev survived the failure
    assert doc is not None and doc["campaign"]["execs"] == 1


def test_checkpoint_components_carry_forward_per_key(tmp_path):
    """A transient get_state() failure on ONE component (its key
    simply missing from the save) must not erase that component's
    last good state from the epoch chain."""
    store = CorpusStore(str(tmp_path))
    store.save_checkpoint({"components": {"mutator": "X",
                                          "instrumentation": "Y"}})
    store.save_checkpoint({"campaign": {"a": 2},
                           "components": {"instrumentation": "Z"}})
    assert store.load_component_state("mutator") == "X"
    assert store.load_component_state("instrumentation") == "Z"


def test_offline_solver_cache_not_shadowed_by_checkpoint(tmp_path):
    """An offline caller (kb-descend round, bench sweep) writing
    solver.json after a loop campaign checkpointed must not have its
    fresher verdicts shadowed by the epoch's stale solver section —
    save_solver_cache writes through a new epoch too."""
    store = CorpusStore(str(tmp_path))
    store.save_checkpoint({"campaign": {"a": 1},
                           "solver": {"0:1": {"status": "solved"}}})
    store2 = CorpusStore(str(tmp_path))     # fresh-process stand-in
    cache = store2.load_solver_cache()
    cache["2:3"] = {"status": "unsat"}
    store2.save_solver_cache(cache)
    got = CorpusStore(str(tmp_path)).load_solver_cache()
    assert got["2:3"]["status"] == "unsat"
    assert got["0:1"]["status"] == "solved"
    # the campaign section survived the solver write-through
    assert CorpusStore(str(tmp_path)).load_state() == {"a": 1}


def test_chaos_configure_from_env(monkeypatch):
    """kbz-worker picks its fault spec up from KBZ_CHAOS (the
    manager_rpc seam in worker._request fires nothing otherwise)."""
    monkeypatch.setenv(
        "KBZ_CHAOS",
        '{"faults": [{"point": "manager_rpc", "mode": "timeout"}]}')
    eng = chaos_mod.configure_from_env()
    assert eng is not None and eng.faults[0].point == "manager_rpc"
    monkeypatch.delenv("KBZ_CHAOS")
    assert chaos_mod.configure_from_env() is None


def test_store_loaders_prefer_checkpoint_then_legacy(tmp_path):
    store = CorpusStore(str(tmp_path))
    # legacy-only layout reads fine (pre-checkpoint campaign)
    store.save_state({"version": 1, "legacy": True})
    store.save_solver_cache({"0:1": {"status": "unsat"}})
    store.save_component_state("mutator", "legacy-state")
    assert store.load_state()["legacy"] is True
    assert store.load_solver_cache()["0:1"]["status"] == "unsat"
    assert store.load_component_state("mutator") == "legacy-state"
    # a checkpoint takes over as the source of truth
    store.save_checkpoint({
        "campaign": {"version": 1, "legacy": False},
        "solver": {"0:1": {"status": "solved"}},
        "components": {"mutator": "ck-state"}})
    assert store.load_state()["legacy"] is False
    assert store.load_solver_cache()["0:1"]["status"] == "solved"
    assert store.load_component_state("mutator") == "ck-state"
    # checkpoint artifacts never masquerade as corpus entries
    assert store.load() == [] and len(store) == 0


def test_event_seq_heals_from_checkpoint_after_log_loss(tmp_path):
    """Rotation + kill + total log loss: the checkpointed high-water
    floors the resumed stream — seq never regresses for cursors."""
    from killerbeez_tpu.telemetry.events import EventLog
    log = EventLog(str(tmp_path), max_bytes=400)
    for i in range(40):
        log.emit("new_path", md5=f"x{i}")
    assert log.rotations > 0            # really rotated
    high = log.next_seq
    log.close()
    store = CorpusStore(str(tmp_path / "corpus"))
    store.save_checkpoint({"event_seq": high})
    # the kill also eats BOTH log generations
    os.unlink(tmp_path / "events.jsonl")
    os.unlink(tmp_path / "events.jsonl.1")
    fresh = EventLog(str(tmp_path))     # tail scan finds nothing
    assert fresh.next_seq == 0
    fresh.ensure_seq_at_least(
        int(store.load_checkpoint()["event_seq"]))
    rec = fresh.emit("flush")
    assert rec["seq"] == high           # monotone across the loss


# ---------------------------------------------------------------------------
# dispatch watchdog
# ---------------------------------------------------------------------------

def test_watchdog_fires_within_2x_deadline():
    stalls = []
    fired = []
    wd = DispatchWatchdog(multiplier=1.0, min_deadline=0.4,
                          max_deadline=0.4,
                          action=lambda: fired.append(
                              time.monotonic()))
    wd.dump_fn = lambda *a: stalls.append(a)
    t0 = time.monotonic()
    with wd.guard("host_transfer"):
        # a synthetically-hung wait: sleep well past the deadline;
        # the monitor thread must fire while we are "stuck"
        while not fired and time.monotonic() - t0 < 3.0:
            time.sleep(0.02)
    assert fired, "watchdog never fired on a hung wait"
    waited = fired[0] - t0
    assert waited <= 2 * 0.4 + 0.1      # within 2x the armed deadline
    assert stalls and stalls[0][0] == "host_transfer"
    wd.stop()


def test_watchdog_no_false_positive_when_disarmed():
    fired = []
    wd = DispatchWatchdog(min_deadline=0.2, max_deadline=0.2,
                          action=lambda: fired.append(1))
    for _ in range(5):
        with wd.guard("dispatch"):
            pass                        # fast waits disarm in time
    time.sleep(0.6)                     # idle time is NOT guarded
    assert not fired
    wd.stop()


def test_watchdog_deadline_scales_from_registry_ema():
    from killerbeez_tpu.telemetry import EmaRate, MetricsRegistry
    reg = MetricsRegistry()
    r = EmaRate()
    r._rate, r._weight = 1000.0, 1.0    # 1000 execs/s, fully warm
    reg.rates["execs"] = r
    wd = DispatchWatchdog(registry=reg, multiplier=10.0,
                          min_deadline=1.0, max_deadline=120.0)
    wd.note_batch(512)
    assert wd.ema_batch_seconds() == pytest.approx(0.512)
    assert wd.deadline() == pytest.approx(5.12)
    # clamped to the ceiling when the EMA says "very slow"
    r._rate = 1.0
    assert wd.deadline() == 120.0
    # cold start (no estimate at all) grants the ceiling: the first
    # dispatch includes XLA compilation and must not false-positive
    wd2 = DispatchWatchdog(min_deadline=1.0, max_deadline=60.0)
    assert wd2.deadline() == 60.0


# ---------------------------------------------------------------------------
# sync backoff (manager partitions)
# ---------------------------------------------------------------------------

class _SyncFuzzerStub:
    """The minimal surface _sync_round touches."""

    def __init__(self):
        from killerbeez_tpu.corpus.schedule import make_scheduler
        from killerbeez_tpu.telemetry import Telemetry
        self.telemetry = Telemetry(None)
        self.scheduler = make_scheduler("rr")
        self.store = None
        self.feedback = 0
        self._seen = {"new_paths": set()}


def test_sync_partition_backoff_decorrelated_and_findings_survive(
        monkeypatch):
    import random
    import urllib.error
    from killerbeez_tpu.corpus.store import CorpusEntry
    from killerbeez_tpu.corpus.sync import CorpusSync
    fz = _SyncFuzzerStub()
    sync = CorpusSync("http://127.0.0.1:1", "c", worker="w",
                      interval_s=1.0, rng=random.Random(0))
    entry = CorpusEntry(b"finding")
    sync.note_entry(entry)

    def down(*a, **k):
        raise urllib.error.URLError("partitioned")
    monkeypatch.setattr(sync, "_request", down)
    reg = fz.telemetry.registry
    backoffs = []
    for i in range(4):
        assert sync.maybe_sync(fz, force=True)
        assert sync.consecutive_failures == i + 1
        assert reg.gauges["sync_consecutive_failures"] == i + 1
        backoffs.append(sync._backoff)
        # decorrelated jitter: at least the interval, capped
        assert sync.interval_s <= sync._backoff <= sync.backoff_cap
    assert len(set(backoffs)) > 1       # jittered, not lockstep
    # interval gate widens by the backoff (no immediate lockstep
    # retry against a just-recovered manager)
    sync._last_sync = time.time()
    assert not sync.maybe_sync(fz)
    # the admitted finding was REQUEUED, not lost: when the manager
    # returns, it is pushed
    sent = []

    def up(payload=None, method="POST", query=""):
        if method == "GET":
            return {"entries": [], "latest": 0}
        sent.append(payload["md5"])
        return {"new": True}
    monkeypatch.setattr(sync, "_request", up)
    assert sync.maybe_sync(fz, force=True)
    assert sync.consecutive_failures == 0 and sync._backoff == 0.0
    assert reg.gauges["sync_consecutive_failures"] == 0
    assert entry.md5 in sent            # no finding lost


def test_sync_chaos_manager_faults(monkeypatch):
    """The chaos ``manager_rpc`` seam: an injected 500 drops the
    entry from sync (HTTP-rejected, never retried), an injected
    partition requeues it."""
    from killerbeez_tpu.corpus.store import CorpusEntry
    from killerbeez_tpu.corpus.sync import CorpusSync
    fz = _SyncFuzzerStub()
    sync = CorpusSync("http://127.0.0.1:1", "c", worker="w",
                      interval_s=0.0)
    chaos_mod.configure({"faults": [
        {"point": "manager_rpc", "mode": "timeout", "hit": 1},
        {"point": "manager_rpc", "mode": "http500", "hit": 2}]})
    e1 = CorpusEntry(b"one")
    sync.note_entry(e1)
    assert sync.maybe_sync(fz, force=True)
    assert sync.consecutive_failures == 1       # partitioned round
    assert sync._pending and sync._pending[0].md5 == e1.md5  # requeued
    # next round: the 500 — manager saw it and refused; dropped
    assert sync.maybe_sync(fz, force=True)
    assert e1.cov_hash in sync._pushed
    assert not sync._pending


# ---------------------------------------------------------------------------
# CLI-level chaos (subprocess: SIGKILL faults must not kill pytest)
# ---------------------------------------------------------------------------

SEED = b"\x00" * 8


def _cli_args(out, extra=()):
    return ["file", "jit_harness", "havoc",
            "-i", '{"target": "cgc_like", "novelty": "throughput"}',
            "-m", '{"seed": 11}', "-fb", "0",
            "-sf", "seed.bin", "-o", out, "-b", "256", "-n", "1024",
            "--corpus-dir", os.path.join(out, "corpus"), *extra]


def _run_cli(tmp_path, args, timeout=180):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO_ROOT +
                os.pathsep + env.get("PYTHONPATH", "")})
    (tmp_path / "seed.bin").write_bytes(SEED)
    return subprocess.run(
        [sys.executable, "-m", "killerbeez_tpu.fuzzer", *args],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=timeout)


def _findings(root):
    out = {}
    for kind in ("crashes", "hangs", "new_paths"):
        d = os.path.join(root, kind)
        out[kind] = sorted(
            n for n in (os.listdir(d) if os.path.isdir(d) else [])
            if len(n) == 32)
    return out


def _store_md5s(root):
    d = os.path.join(root, "corpus")
    return sorted(n for n in os.listdir(d) if len(n) == 32)


def _event_seqs(root):
    seqs = []
    for p in (os.path.join(root, "events.jsonl.1"),
              os.path.join(root, "events.jsonl")):
        if os.path.exists(p):
            for line in open(p):
                if line.strip():
                    seqs.append(json.loads(line)["seq"])
    return seqs


@pytest.fixture(scope="module")
def control_run(tmp_path_factory):
    """The fault-free control campaign every chaos run must converge
    to (same argv, same seed: the candidate stream is deterministic
    with -fb 0)."""
    tmp = tmp_path_factory.mktemp("control")
    r = _run_cli(tmp, _cli_args("ctl"))
    assert r.returncode == 0, r.stderr[-2000:]
    f = _findings(str(tmp / "ctl"))
    assert any(f.values()), "control run found nothing to compare"
    return {"findings": f, "store": _store_md5s(str(tmp / "ctl"))}


@pytest.mark.parametrize("kill_hit", [
    2,
    pytest.param(5, marks=pytest.mark.slow),
    pytest.param(6, marks=pytest.mark.slow),
])
def test_sigkill_at_persistence_point_resume_invariants(
        tmp_path, control_run, kill_hit):
    """SIGKILL at randomized persistence points: after resume, the
    campaign ends with the control run's EXACT findings + corpus
    sets, no duplicate arms, and a monotone event seq.  This argv
    produces exactly 6 persist writes (2 admissions x entry+sidecar
    + interval and final checkpoint epochs): hit 2 lands between the
    finding write and the store write-through, 6 on the run-end
    checkpoint itself."""
    spec = json.dumps({"faults": [
        {"point": "persist", "mode": "kill", "hit": kill_hit}]})
    r = _run_cli(tmp_path, _cli_args("out", ["--chaos", spec]))
    assert r.returncode == -signal.SIGKILL
    r = _run_cli(tmp_path, _cli_args("out", ["--resume"]))
    assert r.returncode == 0, r.stderr[-2000:]
    out = str(tmp_path / "out")
    # no finding lost after admission; no duplicates minted
    assert _findings(out) == control_run["findings"]
    assert _store_md5s(out) == control_run["store"]
    entries = CorpusStore(os.path.join(out, "corpus")).load()
    md5s = [e.md5 for e in entries]
    assert len(md5s) == len(set(md5s))  # no duplicate corpus arms
    seqs = _event_seqs(out)
    assert seqs and all(b > a for a, b in zip(seqs, seqs[1:]))


def test_device_loss_classified_exit_87_and_checkpointed(tmp_path):
    spec = json.dumps({"faults": [
        {"point": "device_dispatch", "mode": "raise", "hit": 2}]})
    r = _run_cli(tmp_path, _cli_args("out", ["--chaos", spec]))
    assert r.returncode == DEVICE_LOST_EXIT_CODE
    assert "device lost" in r.stderr.lower()
    out = tmp_path / "out"
    # run()'s finally checkpointed before the classified exit
    assert (out / "corpus" / "checkpoint.json").exists()
    evs = [json.loads(l) for l in open(out / "events.jsonl")
           if l.strip()]
    assert any(e["type"] == "device_lost" for e in evs)


def test_enospc_everywhere_degrades_but_campaign_completes(tmp_path):
    """Disk full on EVERY persistence write: the campaign must still
    run to completion (warnings, not raises)."""
    spec = json.dumps({"faults": [
        {"point": "persist", "mode": "enospc", "every": 1}]})
    r = _run_cli(tmp_path, _cli_args("out", ["--chaos", spec]))
    assert r.returncode == 0, r.stderr[-2000:]


def test_watchdog_kills_hung_dispatch_within_2x_deadline(tmp_path):
    """Acceptance: a synthetically-hung device wait dies by watchdog
    (exit 86) within 2x the armed deadline, leaving the stall event
    and the in-flight dump."""
    spec = json.dumps({"faults": [
        {"point": "device_wait", "mode": "hang", "hit": 2,
         "seconds": 60}]})
    r = _run_cli(tmp_path, _cli_args("out", [
        "--chaos", spec, "--watchdog", "4",
        "--watchdog-min", "1", "--watchdog-max", "15"]))
    assert r.returncode == WATCHDOG_EXIT_CODE, r.stderr[-2000:]
    out = tmp_path / "out"
    evs = [json.loads(l) for l in open(out / "events.jsonl")
           if l.strip()]
    stalls = [e for e in evs if e["type"] == "watchdog_stall"]
    assert stalls
    s = stalls[0]
    assert s["waited_s"] <= 2 * s["deadline_s"]
    dump = json.loads((out / "watchdog_dump.json").read_text())
    assert dump["stage"] == s["stage"]
    assert isinstance(dump["pending"], list)


def test_events_rotation_plus_solver_kill_resume(tmp_path):
    """Satellite: rotation mid-campaign + a kill + resume, with the
    crack stage's verdicts riding the unified checkpoint — seq stays
    monotone across BOTH generations and the resumed cracker starts
    warm from the checkpoint's solver section."""
    # -b 64: the plateau window is (plateau + PIPELINE_DEPTH) x b, so
    # a small batch lets the crack fire inside -n; the 2KB event cap
    # rotates mid-campaign (~30 events between finds, scheduler
    # picks, plateau/crack records and flushes)
    args = ["file", "jit_harness", "havoc",
            "-i", '{"target": "test", "novelty": "throughput"}',
            "-m", '{"seed": 11}', "-sf", "seed.bin",
            "-o", "out", "-b", "64", "-n", "8192",
            "--corpus-dir", os.path.join("out", "corpus"),
            "--crack", "2", "--events-max-mb", "0.002"]
    spec = json.dumps({"faults": [
        {"point": "event_append", "mode": "kill", "hit": 25}]})
    r = _run_cli(tmp_path, args + ["--chaos", spec])
    assert r.returncode == -signal.SIGKILL
    r = _run_cli(tmp_path, args + ["--resume"])
    assert r.returncode == 0, r.stderr[-2000:]
    out = str(tmp_path / "out")
    assert os.path.exists(os.path.join(out, "events.jsonl.1"))
    seqs = _event_seqs(out)
    assert seqs and all(b > a for a, b in zip(seqs, seqs[1:]))
    ck = CorpusStore(os.path.join(out, "corpus")).load_checkpoint()
    assert any(v.get("status") == "solved"
               for v in ck["solver"].values())
    assert ck["event_seq"] <= max(seqs) + 1
    # a fresh cracker over the store starts warm (no re-solving)
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    from killerbeez_tpu.models.targets import get_target
    prog = get_target("test")
    c2 = BranchCracker(prog,
                       store=CorpusStore(os.path.join(out, "corpus")))
    assert c2.cache == ck["solver"]


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def _stub_child(tmp_path, rcs):
    """A child command that exits with rcs[launch#] and records its
    argv — a fuzzer stand-in for supervisor-policy tests."""
    script = tmp_path / "stub.py"
    script.write_text(
        "import json, os, sys\n"
        "d = os.path.dirname(os.path.abspath(__file__))\n"
        "p = os.path.join(d, 'launches.json')\n"
        "hist = json.load(open(p)) if os.path.exists(p) else []\n"
        "hist.append(sys.argv[1:])\n"
        "json.dump(hist, open(p, 'w'))\n"
        f"rcs = {rcs!r}\n"
        "sys.exit(rcs[min(len(hist) - 1, len(rcs) - 1)])\n")
    return [sys.executable, str(script)]


def _launches(tmp_path):
    return json.load(open(tmp_path / "launches.json"))


def test_supervisor_restarts_into_resume(tmp_path):
    sup = Supervisor(["-o", str(tmp_path / "out")],
                     child_cmd=_stub_child(tmp_path, [1, 0]),
                     backoff_base=0.01, backoff_cap=0.05)
    assert sup.run() == 0
    launches = _launches(tmp_path)
    assert len(launches) == 2 and sup.restarts == 1
    assert "--resume" not in launches[0]
    assert "--resume" in launches[1]            # restart resumes
    assert "--corpus-dir" in launches[0]        # injected: something
    #                                             to resume FROM
    recs = [json.loads(l) for l in
            open(tmp_path / "out" / "supervisor.jsonl")]
    classes = [r.get("class") for r in recs if r["event"] == "exit"]
    assert classes == [CRASH, CLEAN]


def test_supervisor_respects_restart_budget(tmp_path):
    sup = Supervisor(["-o", str(tmp_path / "out")],
                     child_cmd=_stub_child(tmp_path, [1]),
                     max_restarts=2, backoff_base=0.01,
                     backoff_cap=0.02)
    assert sup.run() == 1
    assert len(_launches(tmp_path)) == 3        # initial + 2 restarts


def test_supervisor_backoff_capped_exponential_with_jitter():
    import random
    sup = Supervisor(["-o", "x"], backoff_base=1.0, backoff_cap=8.0,
                     rng=random.Random(0))
    delays = []
    for streak in (1, 2, 3, 4, 5, 6):
        sup.streak = streak
        delays.append(sup.backoff_seconds())
    # jittered around base*2^(n-1), never beyond 1.5x the cap
    assert delays[0] <= 1.5
    assert max(delays) <= 8.0 * 1.5
    assert len(set(delays)) > 1                 # not constant


def test_supervisor_device_loss_probe_and_mesh_degrade(tmp_path):
    sup = Supervisor(["-o", str(tmp_path / "out"), "--mesh", "4,2"],
                     child_cmd=_stub_child(tmp_path, [87, 0]),
                     probe_cmd="echo 4", backoff_base=0.01,
                     backoff_cap=0.02)
    assert sup.run() == 0
    launches = _launches(tmp_path)
    i = launches[1].index("--mesh")
    assert launches[1][i + 1] == "2,2"          # dp=4 -> dp=2
    recs = [json.loads(l) for l in
            open(tmp_path / "out" / "supervisor.jsonl")]
    assert any(r["event"] == "degrade" and r["mesh_to"] == "2,2"
               for r in recs)


def test_supervisor_mesh_degrade_preserves_generations_argv(tmp_path):
    """Satellite (ISSUE 10): a mesh dp-shrink after device loss must
    rebuild the child argv with -G/--generations (and every other
    flag) intact, and pick a dp that still DIVIDES the batch — the
    sharded driver rejects -b % dp at startup, so a merely-fitting
    dp would crash-loop the restart."""
    argv = ["-o", str(tmp_path / "out"), "--mesh", "6,1",
            "-b", "96", "-G", "8", "-fb", "0"]
    sup = Supervisor(argv, child_cmd=_stub_child(tmp_path, [87, 0]),
                     probe_cmd="echo 4", backoff_base=0.01,
                     backoff_cap=0.02)
    assert sup.run() == 0
    launches = _launches(tmp_path)
    rebuilt = launches[1]
    i = rebuilt.index("--mesh")
    # 6 chips -> 4 alive: dp=4 fits but 96 % 4 == 0 too; the pick
    # must divide the batch (96 % 4 == 0 -> "4,1")
    assert rebuilt[i + 1] == "4,1"
    assert rebuilt[rebuilt.index("-G") + 1] == "8"   # preserved
    assert rebuilt[rebuilt.index("-b") + 1] == "96"
    assert "--resume" in rebuilt
    # a divisor-hostile chip count skips the non-divisor: 5 alive
    # with -b 96 must land dp=4 (96 % 5 != 0), not dp=5
    assert shrink_mesh("6,1", 5, batch=96) == "4,1"
    assert shrink_mesh("6,1", 5) == "5,1"       # batch unknown: fit
    assert shrink_mesh("4,2", 4, batch=64) == "2,2"
    assert shrink_mesh("4,2", 1, batch=64) is None


def test_supervisor_native_fallback_when_no_device_returns(tmp_path):
    fallback = f"stdin return_code havoc -o {tmp_path / 'out'}"
    sup = Supervisor(["-o", str(tmp_path / "out")],
                     child_cmd=_stub_child(tmp_path, [87, 0]),
                     probe_cmd="echo 0", probe_attempts=2,
                     fallback=fallback, backoff_base=0.01,
                     backoff_cap=0.02,
                     sleep_fn=lambda s: None)
    assert sup.run() == 0
    launches = _launches(tmp_path)
    assert launches[1][:3] == ["stdin", "return_code", "havoc"]
    assert "--resume" in launches[1]


def test_supervisor_gives_up_without_fallback(tmp_path):
    sup = Supervisor(["-o", str(tmp_path / "out")],
                     child_cmd=_stub_child(tmp_path, [87]),
                     probe_cmd="echo 0", probe_attempts=2,
                     backoff_base=0.01, backoff_cap=0.02,
                     sleep_fn=lambda s: None)
    assert sup.run() == 87
    recs = [json.loads(l) for l in
            open(tmp_path / "out" / "supervisor.jsonl")]
    assert any(r["event"] == "giveup" for r in recs)


def test_supervise_cli_requires_fuzzer_argv(capsys):
    from killerbeez_tpu.resilience.supervisor import main
    assert main(["--max-restarts", "1", "--"]) == 2


def test_supervised_campaign_survives_device_loss_and_sigkill(
        tmp_path, control_run):
    """THE acceptance e2e: a supervised CLI campaign eats an injected
    device loss AND a SIGKILL at a persistence point, restarts into
    --resume each time, and ends with the fault-free control run's
    exact admitted-findings set and a monotone event seq."""
    spec = json.dumps({"seed": 3, "faults": [
        {"point": "device_dispatch", "mode": "raise", "hit": 3},
        {"point": "persist", "mode": "kill", "hit": 6}]})
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO_ROOT +
                os.pathsep + env.get("PYTHONPATH", "")})
    (tmp_path / "seed.bin").write_bytes(SEED)
    r = subprocess.run(
        [sys.executable, "-m", "killerbeez_tpu.resilience.supervisor",
         "--backoff-base", "0.05", "--backoff-cap", "0.2",
         "--probe-cmd", "echo 8", "--chaos", spec,
         "--chaos-launches", "2", "--", *_cli_args("out")],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=420)
    assert r.returncode == 0, r.stderr[-3000:]
    out = str(tmp_path / "out")
    assert _findings(out) == control_run["findings"]
    assert _store_md5s(out) == control_run["store"]
    seqs = _event_seqs(out)
    assert seqs and all(b > a for a, b in zip(seqs, seqs[1:]))
    recs = [json.loads(l)
            for l in open(os.path.join(out, "supervisor.jsonl"))]
    classes = [r.get("class") for r in recs if r["event"] == "exit"]
    # both injected fault families actually fired and were classified
    assert DEVICE_LOST in classes and CRASH in classes
    assert classes[-1] == CLEAN
    assert any(r["event"] == "device_probe" for r in recs)
