"""ipt (trace-hash novelty) and debug (ptrace crash details)
instrumentation tests — reference SURVEY §2.3 behaviors: hash-pair
novelty with set-union merge (linux_ipt semantics) and debugger-grade
crash triage (debug_instrumentation semantics).
"""

import json

import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_NONE
from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.instrumentation.factory import instrumentation_factory


def make_ipt(**opts):
    return instrumentation_factory(
        "ipt", json.dumps({"target": "test", **opts}))


def batch(instr, seeds):
    L = 8
    buf = np.zeros((len(seeds), L), dtype=np.uint8)
    lens = np.zeros(len(seeds), dtype=np.int32)
    for i, s in enumerate(seeds):
        buf[i, :len(s)] = np.frombuffer(s, dtype=np.uint8)
        lens[i] = len(s)
    return instr.run_batch(buf, lens)


def test_ipt_novelty_is_path_sensitive():
    instr = make_ipt()
    res = batch(instr, [b"zzzz", b"zzzz", b"Azzz", b"ABzz", b"ABzz"])
    assert list(res.new_paths) == [1, 0, 1, 1, 0]
    assert list(res.statuses) == [FUZZ_NONE] * 5


def test_ipt_crash_detection_and_uniqueness():
    instr = make_ipt()
    res = batch(instr, [b"ABCD", b"ABCD", b"ABC@"])
    assert list(res.statuses) == [FUZZ_CRASH, FUZZ_CRASH, FUZZ_NONE]
    assert list(res.unique_crashes) == [True, False, False]


def test_ipt_single_exec_shim():
    instr = make_ipt()
    instr.enable(b"ABCD")
    assert instr.get_fuzz_result() == FUZZ_CRASH
    assert instr.is_new_path() == 1
    assert instr.last_unique_crash()
    instr.enable(b"ABCD")
    assert instr.is_new_path() == 0


def test_ipt_state_merge_is_set_union():
    a, b = make_ipt(), make_ipt()
    batch(a, [b"zzzz", b"Azzz"])
    batch(b, [b"Azzz", b"ABzz"])
    before = a.coverage_bytes()
    a.merge(b.get_state())
    assert a.coverage_bytes() == 3  # union of {z, A} and {A, AB}
    assert a.coverage_bytes() > before
    # merged state dedups: replaying b's paths yields nothing new
    res = batch(a, [b"Azzz", b"ABzz"])
    assert not res.new_paths.any()


def test_ipt_state_roundtrip():
    a = make_ipt()
    batch(a, [b"zzzz", b"ABCD"])
    b = make_ipt()
    b.set_state(a.get_state())
    assert b.coverage_bytes() == a.coverage_bytes()
    res = batch(b, [b"zzzz"])
    assert not res.new_paths.any()


def test_ipt_filters_restrict_tracing():
    """With every block id filtered out, all paths hash identically:
    only the first exec is 'new' (reference address-filter behavior:
    untraced regions contribute nothing)."""
    instr = make_ipt(filters=[[0, 1]])
    res = batch(instr, [b"zzzz", b"Azzz", b"ABzz"])
    assert list(res.new_paths) == [1, 0, 0]


def test_ipt_foreign_hash_scheme_degrades_not_raises():
    """A state from a differently-filtered instance (or a pre-0.2
    state with no hash_scheme key) lives in a different 64-bit hash
    space: set_state starts fresh but keeps counters, merge is a
    no-op — neither raises (cross-version manager compat)."""
    a = make_ipt()                      # unfiltered: "path+counts"
    batch(a, [b"zzzz", b"Azzz"])
    foreign = json.loads(a.get_state())
    foreign["hash_scheme"] = "stream"   # simulate a filtered instance
    b = make_ipt()
    b.merge(json.dumps(foreign))        # no-op, not ValueError
    assert b.coverage_bytes() == 0
    b.set_state(json.dumps(foreign))    # fresh sets, counters kept
    assert b.coverage_bytes() == 0
    assert b.total_execs == a.total_execs
    # pre-0.2 states carry no key at all: defaults to "stream"
    del foreign["hash_scheme"]
    c = make_ipt()
    c.set_state(json.dumps(foreign))
    assert c.coverage_bytes() == 0
    # like-configured states still roundtrip fully
    d = make_ipt()
    d.set_state(a.get_state())
    assert d.coverage_bytes() == a.coverage_bytes()


def test_ipt_without_target_mentions_host_mode():
    with pytest.raises(ValueError, match="qemu_mode"):
        instrumentation_factory("ipt", None)


def test_ipt_host_binary_hash_coverage(corpus_bin, kb_trace_usable):
    """The host-binary ipt tier (reference
    linux_ipt_instrumentation.c:212-426 role): an UNINSTRUMENTED
    binary under kb-trace hash mode gets path-sensitive (tip, tnt)
    pair novelty — distinct compare-fail paths are distinct pairs,
    repeats are not novel, crash pairs drive uniqueness."""
    instr = instrumentation_factory("ipt", json.dumps(
        {"qemu_mode": 1}))
    try:
        tgt = corpus_bin("test-plain")
        instr.enable(b"zzzz", cmd_line=tgt)
        assert instr.get_fuzz_result() == FUZZ_NONE
        assert instr.is_new_path() == 1
        instr.enable(b"zzzz", cmd_line=tgt)
        assert instr.is_new_path() == 0          # same path
        instr.enable(b"ABCD", cmd_line=tgt)
        assert instr.get_fuzz_result() == FUZZ_CRASH
        assert instr.last_unique_crash()
        instr.enable(b"ABCD", cmd_line=tgt)
        assert not instr.last_unique_crash()     # same crash path
        instr.enable(b"ABXD", cmd_line=tgt)
        assert instr.is_new_path() == 1          # divergence at byte 2
        assert instr.coverage_bytes() == 3       # 3 distinct paths
        # batch path agrees with the single-exec loop
        instr.prepare_host(tgt, use_stdin=True)
        inputs = np.zeros((3, 4), np.uint8)
        for i, s in enumerate([b"zzzz", b"ABXD", b"AXCD"]):
            inputs[i, :4] = np.frombuffer(s, np.uint8)
        res = instr.run_batch(inputs, np.full(3, 4, np.int32))
        assert list(res.new_paths) == [0, 0, 1]
    finally:
        instr.cleanup()


def test_ipt_host_state_merge_is_set_union(corpus_bin,
                                           kb_trace_usable):
    """Host-tier states merge as set union (reference merger fold)
    and carry their own hash-space tag."""
    tgt = corpus_bin("test-plain")
    a = instrumentation_factory("ipt", json.dumps({"qemu_mode": 1}))
    b = instrumentation_factory("ipt", json.dumps({"qemu_mode": 1}))
    try:
        a.enable(b"zzzz", cmd_line=tgt)
        a.enable(b"ABXD", cmd_line=tgt)
        b.enable(b"zzzz", cmd_line=tgt)
        b.enable(b"AXCD", cmd_line=tgt)
        union = a.hashes | b.hashes
        a.merge(b.get_state())
        assert a.hashes == union and len(union) == 3
        assert json.loads(a.get_state())["hash_scheme"] == "host-block"
        # VM-space states do not pollute host-space sets
        vm = instrumentation_factory("ipt", '{"target": "test"}')
        vm.enable(b"zzzz")
        before = set(a.hashes)
        a.merge(vm.get_state())
        assert a.hashes == before
    finally:
        a.cleanup()
        b.cleanup()


def test_debug_crash_details(corpus_bin):
    instr = instrumentation_factory("debug", None)
    drv = driver_factory("stdin", json.dumps(
        {"path": corpus_bin("test-plain")}), instr, None)
    assert drv.test_input(b"ABCD") == FUZZ_CRASH
    info = instr.last_crash_info
    assert info["signal"] == 11          # SIGSEGV
    assert info["fault_addr"] == 0       # the NULL write
    assert info["pc"] > 0
    assert "SIGSEGV" in instr.crash_description()
    assert instr.last_unique_crash()
    # same site again: crash but not unique
    assert drv.test_input(b"ABCD") == FUZZ_CRASH
    assert not instr.last_unique_crash()
    assert drv.test_input(b"ABC@") == FUZZ_NONE
    assert instr.is_new_path() == 0      # no coverage, like reference
    drv.cleanup()
    instr.cleanup()


def test_debug_sigtrap_is_a_crash(corpus_bin):
    """Regression: only the single post-execve SIGTRAP may be
    suppressed — a later int3 is a real breakpoint crash."""
    instr = instrumentation_factory("debug", None)
    drv = driver_factory("stdin", json.dumps(
        {"path": corpus_bin("crashers")}), instr, None)
    assert drv.test_input(b"TRAP") == FUZZ_CRASH
    assert instr.last_crash_info["signal"] == 5  # SIGTRAP
    drv.cleanup()
    instr.cleanup()


def test_debug_library_crash_pc_stable(corpus_bin):
    """Regression: the PC normalizes against the base of the module
    CONTAINING the fault (libc here), so re-running the same
    library crash dedups instead of minting a new site per ASLR
    layout."""
    instr = instrumentation_factory("debug", None)
    drv = driver_factory("stdin", json.dumps(
        {"path": corpus_bin("crashers")}), instr, None)
    pcs = []
    for _ in range(3):
        assert drv.test_input(b"LIBC") == FUZZ_CRASH
        pcs.append(instr.last_crash_info["pc"])
    assert pcs[0] == pcs[1] == pcs[2]
    assert len(instr.crash_sites) == 1
    # abort() is a distinct signal/site
    assert drv.test_input(b"ABRT") == FUZZ_CRASH
    assert instr.last_crash_info["signal"] == 6  # SIGABRT
    assert len(instr.crash_sites) == 2
    drv.cleanup()
    instr.cleanup()


def test_debug_state_merge(corpus_bin):
    a = instrumentation_factory("debug", None)
    drv = driver_factory("stdin", json.dumps(
        {"path": corpus_bin("test-plain")}), a, None)
    drv.test_input(b"ABCD")
    b = instrumentation_factory("debug", None)
    b.merge(a.get_state())
    assert b.crash_sites == a.crash_sites and b.crash_sites
    drv.cleanup()
    a.cleanup()
    b.cleanup()


def test_ipt_error_lanes_skip_novelty_sets():
    """A FUZZ_ERROR lane publishes a zeroed bitmap: its (tip, tnt)
    pair 0 is not a path identity and must not enter the hash sets —
    the first exec error in a campaign used to count once as a new
    path and record the offending input as a finding."""
    from killerbeez_tpu import FUZZ_ERROR
    instr = make_ipt()
    statuses = np.array([FUZZ_ERROR, FUZZ_NONE, FUZZ_ERROR],
                        dtype=np.int32)
    res = instr._update_sets(statuses, [0, 0, 0],
                             np.zeros(3, dtype=np.int32))
    # error lanes report nothing; the genuine pair-0 exec still
    # counts exactly once
    assert res.new_paths.tolist() == [0, 1, 0]
    assert not res.unique_crashes.any() and not res.unique_hangs.any()
    assert instr.hashes == {0}
    # a later crash on pair 0 is still judged against a set the
    # error lanes never polluted
    res2 = instr._update_sets(np.array([FUZZ_CRASH], dtype=np.int32),
                              [0], np.zeros(1, dtype=np.int32))
    assert res2.new_paths.tolist() == [0]
    assert res2.unique_crashes.tolist() == [True]
