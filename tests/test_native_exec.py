"""Native exec backend + forkserver protocol tests (native/kb_exec.cpp,
kb_rt.c, kb_preload.c) against the corpus fixture binaries.

Mirrors the reference's smoke-test style behavioral assertions
(SURVEY §4): crash on the full magic, no crash one byte short, hang
detection by timeout, forkserver vs plain spawn equivalence,
persistence, preload forkserver, and coverage monotonicity as the
input homes in on the magic.
"""

import json
import os

import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_HANG, FUZZ_NONE
from killerbeez_tpu.native.exec_backend import (
    ExecTarget, KB_MAP_SIZE, classify,
)


def test_classify_codes():
    assert classify(0) == (FUZZ_NONE, 0)
    assert classify(7) == (FUZZ_NONE, 7)
    assert classify(512 + 11) == (FUZZ_CRASH, 11)
    assert classify(-1) == (FUZZ_HANG, -1)


@pytest.mark.parametrize("use_forkserver", [False, True])
def test_crash_verdicts(corpus_bin, use_forkserver):
    with ExecTarget([corpus_bin("test")], use_stdin=True,
                    use_forkserver=use_forkserver, coverage=True,
                    timeout=2.0) as t:
        assert classify(t.run(b"ABC@"))[0] == FUZZ_NONE
        assert classify(t.run(b"ABCD"))[0] == FUZZ_CRASH
        assert classify(t.run(b"zzzz"))[0] == FUZZ_NONE


def test_hang_detection(corpus_bin):
    with ExecTarget([corpus_bin("hang")], use_stdin=True,
                    use_forkserver=True, timeout=0.3) as t:
        assert classify(t.run(b"Hang"))[0] == FUZZ_HANG
        # the forkserver survives the killed hang
        assert classify(t.run(b"okay"))[0] == FUZZ_NONE


def test_coverage_deepens_with_prefix(corpus_bin):
    """Each matched magic byte enters a new block: strictly more edges."""
    with ExecTarget([corpus_bin("test")], use_stdin=True,
                    use_forkserver=True, coverage=True) as t:
        counts = []
        for s in (b"zzzz", b"Azzz", b"ABzz", b"ABCz"):
            t.clear_trace()
            t.run(s)
            counts.append(int((t.trace_bits() != 0).sum()))
        assert counts == sorted(counts)
        assert counts[0] < counts[-1]


def test_coverage_slots_stable_across_instances(corpus_bin):
    """ASLR normalization (kb_rt anchor): two INDEPENDENT instances of
    the same PIE binary must agree on bitmap slots, or cross-process
    state merge (merger tool, ICI bitmap allreduce) is meaningless."""
    maps = []
    for _ in range(2):
        with ExecTarget([corpus_bin("test")], use_stdin=True,
                        use_forkserver=True, coverage=True) as t:
            t.clear_trace()
            t.run(b"ABzz")
            maps.append(t.trace_bits().copy())
    assert maps[0].any()
    assert np.array_equal(maps[0], maps[1])


def test_coverage_deterministic(corpus_bin):
    with ExecTarget([corpus_bin("test")], use_stdin=True,
                    use_forkserver=True, coverage=True) as t:
        t.clear_trace()
        t.run(b"ABzz")
        a = t.trace_bits().copy()
        t.clear_trace()
        t.run(b"ABzz")
        assert np.array_equal(a, t.trace_bits())


def test_file_mode(corpus_bin, tmp_path):
    f = str(tmp_path / "input")
    with ExecTarget([corpus_bin("test"), f], input_file=f,
                    use_forkserver=True, coverage=True) as t:
        assert classify(t.run(b"ABCD"))[0] == FUZZ_CRASH
        assert classify(t.run(b"ABC@"))[0] == FUZZ_NONE


def test_run_batch_statuses_and_bitmaps(corpus_bin):
    with ExecTarget([corpus_bin("test")], use_stdin=True,
                    use_forkserver=True, coverage=True) as t:
        seeds = [b"AAAA", b"ABAA", b"ABCA", b"ABCD"]
        inputs = np.zeros((4, 8), dtype=np.uint8)
        for i, s in enumerate(seeds):
            inputs[i, :4] = np.frombuffer(s, dtype=np.uint8)
        lens = np.full(4, 4, dtype=np.int32)
        sts, bms = t.run_batch(inputs, lens)
        assert bms.shape == (4, KB_MAP_SIZE)
        verdicts = [classify(int(s))[0] for s in sts]
        assert verdicts == [FUZZ_NONE, FUZZ_NONE, FUZZ_NONE, FUZZ_CRASH]
        edge_counts = (bms != 0).sum(axis=1)
        assert edge_counts[0] < edge_counts[2]


def test_preload_forkserver_uninstrumented(corpus_bin):
    """LD_PRELOAD forkserver gives fork-per-exec on a plain binary."""
    with ExecTarget([corpus_bin("test-plain")], use_stdin=True,
                    use_forkserver=True,
                    use_preload_forkserver=True) as t:
        assert classify(t.run(b"ABCD"))[0] == FUZZ_CRASH
        assert classify(t.run(b"ABC@"))[0] == FUZZ_NONE
        assert classify(t.run(b"ABCD"))[0] == FUZZ_CRASH


def test_persistence_mode(corpus_bin):
    """One process serves many inputs; crashes still detected and the
    process is recycled after max_cnt iterations."""
    with ExecTarget([corpus_bin("test-persist")], use_stdin=True,
                    use_forkserver=True, coverage=True,
                    persistent=4) as t:
        verdicts = [classify(t.run(s))[0]
                    for s in [b"AAAA"] * 6 + [b"ABCD", b"AAAA"]]
        assert verdicts[:6] == [FUZZ_NONE] * 6
        assert verdicts[6] == FUZZ_CRASH
        assert verdicts[7] == FUZZ_NONE  # re-forked after the crash


def test_persistence_runs_input_staged_at_recycle_boundary(corpus_bin):
    """Regression: the exec that triggers process recycling must still
    run its staged input. kb_rt checks the iteration cap BEFORE the
    SIGSTOP boundary, so a capped child exits without consuming the
    next staged input — if the cap were checked after the stop, the
    crasher staged for exec 3 here would be swallowed by a child that
    only woke up to die (and reported as a clean exit)."""
    with ExecTarget([corpus_bin("test-persist")], use_stdin=True,
                    use_forkserver=True, coverage=True,
                    persistent=2) as t:
        assert classify(t.run(b"AAAA"))[0] == FUZZ_NONE
        assert classify(t.run(b"AAAA"))[0] == FUZZ_NONE  # cap reached
        assert classify(t.run(b"ABCD"))[0] == FUZZ_CRASH


def test_deferred_startup(corpus_bin):
    """KB_DEFER_FORKSRV=1: the runtime constructor skips the
    forkserver; test.c's __kb_manual_init() call at the top of main
    starts it there instead."""
    with ExecTarget([corpus_bin("test-deferred")], use_stdin=True,
                    use_forkserver=True, coverage=True,
                    deferred=True) as t:
        assert classify(t.run(b"ABC@"))[0] == FUZZ_NONE
        assert classify(t.run(b"ABCD"))[0] == FUZZ_CRASH
        assert classify(t.run(b"ABC@"))[0] == FUZZ_NONE


def test_forkserver_restarts_after_exit(corpus_bin):
    with ExecTarget([corpus_bin("test")], use_stdin=True,
                    use_forkserver=True, coverage=True) as t:
        t.run(b"AAAA")
        t.stop()
        # next run transparently restarts the forkserver
        assert classify(t.run(b"ABCD"))[0] == FUZZ_CRASH


def test_exec_pool_matches_single_instance(corpus_bin):
    """ExecPool shards a batch over N forkservers; statuses and
    bitmaps must line up with the single-instance run, in order."""
    from killerbeez_tpu.native.exec_backend import ExecPool
    inputs = np.zeros((8, 4), dtype=np.uint8)
    seqs = [b"ABCD", b"zzzz", b"ABC@", b"ABCD", b"aaaa", b"ABzz",
            b"ABCD", b"Azzz"]
    for i, s in enumerate(seqs):
        inputs[i, :len(s)] = np.frombuffer(s, dtype=np.uint8)
    lengths = np.full(8, 4, dtype=np.int32)

    with ExecTarget([corpus_bin("test")], use_stdin=True,
                    use_forkserver=True, coverage=True) as solo:
        s_stat, s_maps = solo.run_batch(inputs, lengths)
    with ExecPool([corpus_bin("test")], 4, use_stdin=True,
                  use_forkserver=True, coverage=True) as pool:
        p_stat, p_maps = pool.run_batch(inputs, lengths)
    np.testing.assert_array_equal(s_stat, p_stat)
    np.testing.assert_array_equal(s_maps, p_maps)
    crash_rows = [classify(int(x))[0] == FUZZ_CRASH for x in p_stat]
    assert crash_rows == [s == b"ABCD" for s in seqs]


def test_afl_workers_option(corpus_bin):
    """The afl instrumentation's workers option builds a pool and the
    batched path keeps exact counts."""
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.native.exec_backend import ExecPool
    instr = instrumentation_factory("afl", '{"workers": 3}')
    instr.prepare_host(corpus_bin("test"), use_stdin=True)
    assert isinstance(instr._target, ExecPool)
    inputs = np.zeros((6, 4), dtype=np.uint8)
    for i, s in enumerate([b"ABCD", b"zzzz", b"ABC@", b"yyyy",
                           b"ABCD", b"ABCz"]):
        inputs[i, :len(s)] = np.frombuffer(s, dtype=np.uint8)
    res = instr.run_batch(inputs, np.full(6, 4, dtype=np.int32))
    assert (res.statuses == 2).sum() == 2          # both ABCD lanes
    assert instr.total_execs == 6
    instr.cleanup()


def test_qemu_mode_binary_only_coverage(corpus_bin, kb_trace_usable):
    """Binary-only targets (reference afl_progs qemu_mode): with
    qemu_mode=1 the UNINSTRUMENTED test-plain binary runs under the
    bundled kb-trace ptrace tracer, which acts as the forkserver and
    fills the __AFL_SHM_ID bitmap with block-granular edges from the
    main image (branch-step inside the image, breakpointed native
    execution elsewhere, fork-at-main template) — crash
    classification AND coverage novelty with zero target
    cooperation.  Any other __AFL_SHM_ID-honoring emulator plugs in
    via qemu_path."""
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    instr = instrumentation_factory("afl", json.dumps(
        {"qemu_mode": 1}))  # qemu_path defaults to bundled kb-trace
    try:
        instr.enable(b"zzzz", cmd_line=corpus_bin("test-plain"))
        assert instr.get_fuzz_result() == FUZZ_NONE
        assert instr.is_new_path() > 0        # first exec: coverage
        first_cov = instr.coverage_bytes()
        assert first_cov > 20                 # real per-block bitmap
        instr.enable(b"zzzz", cmd_line=corpus_bin("test-plain"))
        assert instr.is_new_path() == 0       # same path: nothing new
        instr.enable(b"ABCD", cmd_line=corpus_bin("test-plain"))
        assert instr.get_fuzz_result() == FUZZ_CRASH
        assert instr.last_unique_crash()
        assert instr.is_new_path() > 0        # crash path differs
        assert instr.coverage_bytes() > first_cov
        instr.enable(b"ABCD", cmd_line=corpus_bin("test-plain"))
        assert instr.get_fuzz_result() == FUZZ_CRASH
        assert not instr.last_unique_crash()  # same crash shape
    finally:
        instr.cleanup()


def test_untracer_mode_map_parity(corpus_bin, kb_trace_usable,
                                  monkeypatch):
    """UnTracer mode (default) vs full block-stepping
    (KB_TRACE_FULL=1): for a novelty-bearing input the re-run must
    rebuild the IDENTICAL map the full engine produces, and a
    repeated input must report nothing new in both modes."""
    import json as _json
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )

    def coverage_for(env_full):
        if env_full:
            monkeypatch.setenv("KB_TRACE_FULL", "1")
        else:
            monkeypatch.delenv("KB_TRACE_FULL", raising=False)
        instr = instrumentation_factory("afl", _json.dumps(
            {"qemu_mode": 1}))
        try:
            instr.enable(b"zzzz", cmd_line=corpus_bin("test-plain"))
            assert instr.is_new_path() > 0
            nbytes = instr.coverage_bytes()
            instr.enable(b"zzzz", cmd_line=corpus_bin("test-plain"))
            assert instr.is_new_path() == 0
            return nbytes
        finally:
            instr.cleanup()

    fast_bytes = coverage_for(False)
    full_bytes = coverage_for(True)
    assert fast_bytes == full_bytes


def test_qemu_mode_plain_exec(corpus_bin, kb_trace_usable):
    """qemu_mode with use_fork_server=0: one tracer process per exec
    (the reference's -Q without forkserver); verdicts still come
    from the traced child's status."""
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    instr = instrumentation_factory("afl", json.dumps(
        {"qemu_mode": 1, "use_fork_server": 0}))
    try:
        instr.enable(b"ABCD", cmd_line=corpus_bin("test-plain"))
        assert instr.get_fuzz_result() == FUZZ_CRASH
        instr.enable(b"zzzz", cmd_line=corpus_bin("test-plain"))
        assert instr.get_fuzz_result() == FUZZ_NONE
    finally:
        instr.cleanup()


def test_qemu_mode_rejects_missing_tracer():
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    with pytest.raises(ValueError, match="qemu_mode"):
        instrumentation_factory("afl", json.dumps(
            {"qemu_mode": 1, "qemu_path": "/nonexistent/qemu"}))


def test_afl_workers_file_delivery(corpus_bin):
    """workers>1 with file (@@) delivery: each pool worker derives a
    private input file, so file-mode targets scale like stdin ones
    (reference per-instance input files,
    dynamorio_instrumentation.c:418-431)."""
    import tempfile
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.native.exec_backend import ExecPool
    instr = instrumentation_factory("afl", json.dumps({"workers": 3}))
    tf = tempfile.mktemp(prefix="kbz_in_")
    try:
        instr.prepare_host(f'{corpus_bin("test")} {tf}',
                           use_stdin=False, input_file=tf)
        assert isinstance(instr._target, ExecPool)
        assert instr._target.n_workers == 3
        inputs = np.zeros((6, 4), dtype=np.uint8)
        for i, s in enumerate([b"ABCD", b"zzzz", b"ABC@", b"yyyy",
                               b"ABCD", b"ABCz"]):
            inputs[i, :len(s)] = np.frombuffer(s, dtype=np.uint8)
        res = instr.run_batch(inputs, np.full(6, 4, dtype=np.int32))
        assert (res.statuses == 2).sum() == 2      # both ABCD lanes
        assert instr.total_execs == 6
    finally:
        instr.cleanup()


def test_qemu_path_external_emulator(corpus_bin):
    """The qemu_path interop claim (afl.py options): ANY external
    __AFL_SHM_ID-honoring emulator plugs in.  corpus/qemu_stub.c is
    built from the documented wire contract alone (no killerbeez
    headers); campaigns through it must get verdicts AND
    input-dependent coverage novelty."""
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    instr = instrumentation_factory("afl", json.dumps(
        {"qemu_mode": 1, "qemu_path": corpus_bin("qemu-stub")}))
    try:
        tgt = corpus_bin("test-plain")
        instr.enable(b"zzzz", cmd_line=tgt)
        assert instr.get_fuzz_result() == FUZZ_NONE
        assert instr.is_new_path() > 0          # first exec: coverage
        instr.enable(b"zzzz", cmd_line=tgt)
        assert instr.is_new_path() == 0         # same input: no new
        instr.enable(b"zzyy", cmd_line=tgt)
        assert instr.is_new_path() > 0          # diverging input: new
        instr.enable(b"ABCD", cmd_line=tgt)
        assert instr.get_fuzz_result() == FUZZ_CRASH  # real verdicts
        # batch path through the same external emulator
        instr.prepare_host(tgt, use_stdin=True)
        inputs = np.zeros((3, 4), np.uint8)
        for i, s in enumerate([b"zzzz", b"ABCD", b"qqqq"]):
            inputs[i, :4] = np.frombuffer(s, np.uint8)
        res = instr.run_batch(inputs, np.full(3, 4, np.int32))
        assert res.statuses[1] == FUZZ_CRASH
        assert res.new_paths[2] > 0
    finally:
        instr.cleanup()
