"""Side-tool tests (tools/: merger, tracer, minimize, picker,
showmap) — reference SURVEY §2.7 behaviors: state merging as the
offline coverage allreduce, deterministic-edge intersection, greedy
edge-cover minimization (mirrors the reference minimizer_test), and
the afl-showmap self-test property (different inputs -> different
maps).
"""

import json
import os

import numpy as np
import pytest

from killerbeez_tpu import MAP_SIZE
from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.tools.merger import main as merger_main
from killerbeez_tpu.tools.minimize import (
    greedy_edge_cover, main as minimize_main,
)
from killerbeez_tpu.tools.picker import (
    classify_target, derive_ignore_mask, main as picker_main,
)
from killerbeez_tpu.tools.showmap import main as showmap_main
from killerbeez_tpu.tools.tracer import (
    main as tracer_main, read_edge_file,
)
from killerbeez_tpu.utils.serialization import decode_array


def run_and_get_state(corpus_bin, tmp_path, seed: bytes, name: str) -> str:
    """One afl exec on the test target; dump state to a file."""
    instr = instrumentation_factory("afl", None)
    drv = driver_factory("stdin", json.dumps(
        {"path": corpus_bin("test")}), instr, None)
    drv.test_input(seed)
    path = str(tmp_path / name)
    with open(path, "w") as f:
        f.write(instr.get_state())
    cov = instr.coverage_bytes()
    drv.cleanup()
    instr.cleanup()
    return path, cov


def test_merger_cli_folds_coverage(corpus_bin, tmp_path):
    s1, cov1 = run_and_get_state(corpus_bin, tmp_path, b"zzzz", "s1")
    s2, cov2 = run_and_get_state(corpus_bin, tmp_path, b"ABCz", "s2")
    out = str(tmp_path / "merged")
    assert merger_main(["afl", s1, s2, "-o", out]) == 0
    instr = instrumentation_factory("afl", None)
    with open(out) as f:
        instr.set_state(f.read())
    merged_cov = instr.coverage_bytes()
    # merged = union: at least each input's coverage, at most the sum
    # (the two paths share the common prefix blocks)
    assert max(cov1, cov2) <= merged_cov < cov1 + cov2
    instr.cleanup()


def test_tracer_deterministic_edges_afl(corpus_bin, tmp_path):
    seed = str(tmp_path / "seed")
    with open(seed, "wb") as f:
        f.write(b"ABzz")
    out = str(tmp_path / "edges.txt")
    assert tracer_main([
        "file", "afl", "-sf", seed, "-o", out, "-n", "3",
        "-d", json.dumps({"path": corpus_bin("test"),
                          "arguments": "@@"})]) == 0
    edges = read_edge_file(out)
    assert edges  # the fixture is deterministic: edges survive
    # deeper input -> strictly more deterministic edges
    seed2 = str(tmp_path / "seed2")
    with open(seed2, "wb") as f:
        f.write(b"ABCz")
    out2 = str(tmp_path / "edges2.txt")
    assert tracer_main([
        "file", "afl", "-sf", seed2, "-o", out2, "-n", "3",
        "-d", json.dumps({"path": corpus_bin("test"),
                          "arguments": "@@"})]) == 0
    assert len(read_edge_file(out2)) > len(edges)


def test_tracer_jit_harness(tmp_path):
    seed = str(tmp_path / "seed")
    with open(seed, "wb") as f:
        f.write(b"ABzz")
    out = str(tmp_path / "edges.txt")
    assert tracer_main([
        "file", "jit_harness", "-sf", seed, "-o", out,
        "-i", json.dumps({"target": "test"})]) == 0
    assert read_edge_file(out)


def test_greedy_edge_cover_order_and_minimality():
    """Mirror of the reference minimizer_test: synthetic edge rows."""
    sets = {
        "big": {1, 2, 3, 4},
        "sub": {1, 2},           # subset of big: never picked
        "extra": {5},
        "dup_extra": {5},        # tie: lexically smaller wins
    }
    kept = greedy_edge_cover(sets)
    assert kept[0] == "big"
    assert "sub" not in kept
    assert ("extra" in kept) != ("dup_extra" in kept)
    assert "dup_extra" in kept  # lexical tiebreak


def test_minimize_cli(tmp_path):
    files = {}
    for name, edges in (("a", {1: 1, 2: 1}), ("b", {2: 1}),
                        ("c", {3: 1})):
        p = str(tmp_path / f"{name}.txt")
        with open(p, "w") as f:
            f.writelines(f"{e}:{c}\n" for e, c in edges.items())
        files[name] = p
    out = str(tmp_path / "keep.txt")
    assert minimize_main([files["a"], files["b"], files["c"],
                          "-o", out]) == 0
    kept = open(out).read().split()
    assert files["a"] in kept and files["c"] in kept
    assert files["b"] not in kept  # subset of a


def test_picker_deterministic_target(corpus_bin, tmp_path):
    seeds = []
    for i, s in enumerate((b"zzzz", b"ABzz")):
        p = str(tmp_path / f"seed{i}")
        with open(p, "wb") as f:
            f.write(s)
        seeds.append(p)
    out = str(tmp_path / "mask.json")
    assert picker_main([
        "file", "afl", *seeds, "-o", out, "-n", "3",
        "-d", json.dumps({"path": corpus_bin("test"),
                          "arguments": "@@"})]) == 0
    report = json.load(open(out))
    # the fixture is fully deterministic: empty mask, per-file paths
    assert report["nondeterministic_bytes"] == 0
    assert report["classification"] == "path_per_file"
    mask = decode_array(report["ignore_bytes"])
    assert mask.shape == (MAP_SIZE,) and not mask.any()


def test_picker_mask_feeds_afl_novelty(corpus_bin, tmp_path):
    """An all-ignore mask kills every novelty signal end-to-end."""
    mask = np.ones(MAP_SIZE, dtype=np.uint8)
    from killerbeez_tpu.utils.serialization import encode_array
    mask_file = str(tmp_path / "mask.json")
    with open(mask_file, "w") as f:
        json.dump({"ignore_bytes": encode_array(mask)}, f)
    instr = instrumentation_factory(
        "afl", json.dumps({"ignore_bytes_file": mask_file}))
    drv = driver_factory("stdin", json.dumps(
        {"path": corpus_bin("test")}), instr, None)
    drv.test_input(b"ABCz")
    assert instr.is_new_path() == 0  # everything masked out
    drv.cleanup()
    instr.cleanup()


def test_derive_ignore_mask_flags_unstable_bytes():
    traces = np.zeros((2, 3, MAP_SIZE), dtype=np.uint8)
    traces[:, :, 10] = 1          # stable byte everywhere
    traces[0, 1, 20] = 7          # varies across runs of seed 0
    traces[1, :, 30] = 2          # stable within seed 1
    traces[0, :, 40] = 5          # differs BETWEEN seeds only: stable
    mask = derive_ignore_mask(traces)
    assert mask[20] == 1
    assert mask[10] == 0 and mask[30] == 0 and mask[40] == 0
    assert classify_target(traces) == "multi_path_same_file"


def test_showmap_differs_between_inputs(corpus_bin, tmp_path, capsys):
    """afl-showmap self-test parity (afl_progs/Makefile:66-74): two
    different inputs must print different maps."""
    outs = []
    for i, s in enumerate((b"zzzz", b"ABCz")):
        seed = str(tmp_path / f"s{i}")
        with open(seed, "wb") as f:
            f.write(s)
        assert showmap_main([
            "file", "afl", "-sf", seed,
            "-d", json.dumps({"path": corpus_bin("test"),
                              "arguments": "@@"})]) == 0
        outs.append(capsys.readouterr().out)
    assert outs[0] and outs[1] and outs[0] != outs[1]


def test_tracer_pairs_per_module(tmp_path):
    """Reference-parity edge records: from:to text lines, one file
    per module (tracer/main.c:254-270)."""
    from killerbeez_tpu.tools.tracer import read_pair_file
    seed = str(tmp_path / "seed")
    with open(seed, "wb") as f:
        f.write(b"LX")
    out = str(tmp_path / "edges")
    assert tracer_main([
        "file", "jit_harness", "-sf", seed, "-o", out, "-f", "pairs",
        "-i", json.dumps({"target": "libtest"})]) == 0
    main_pairs = read_pair_file(out + ".target")
    lib_pairs = read_pair_file(out + ".libtest1")
    assert main_pairs and lib_pairs
    # module files are disjoint record sets over (from, to)
    assert not (main_pairs & lib_pairs)
    # non-library input -> empty library module file
    seed2 = str(tmp_path / "seed2")
    with open(seed2, "wb") as f:
        f.write(b"QQ")
    out2 = str(tmp_path / "e2")
    assert tracer_main([
        "file", "jit_harness", "-sf", seed2, "-o", out2, "-f", "pairs",
        "-i", json.dumps({"target": "libtest"})]) == 0
    assert read_pair_file(out2 + ".libtest1") == set()


def test_minimize_consumes_pair_files(tmp_path):
    """The minimizer's greedy cover runs over from:to records, the
    reference's tracer_info data model."""
    from killerbeez_tpu.tools.minimize import minimize_edge_files
    from killerbeez_tpu.tools.tracer import write_pair_file
    a = str(tmp_path / "a.txt")
    b = str(tmp_path / "b.txt")
    c = str(tmp_path / "c.txt")
    write_pair_file(a, {(1, 2), (2, 3), (3, 4)})
    write_pair_file(b, {(1, 2)})                  # subset: dropped
    write_pair_file(c, {(9, 9)})
    kept, covered = minimize_edge_files([a, b, c], pairs=True)
    assert set(kept) == {a, c}
    assert covered == 4


def test_picker_per_module_masks(corpus_bin, tmp_path):
    """Reference picker walks modules (picker/main.c:163-282): the
    ndlib fixture's main binary is deterministic while its kb-cc
    shared library branches on the clock — the per-module report
    must flag ONLY the library partition, with partition-local
    masks."""
    seed = str(tmp_path / "seed")
    with open(seed, "wb") as f:
        f.write(b"NQxx")
    out = str(tmp_path / "mods.json")
    assert picker_main([
        "file", "afl", seed, "-o", out, "-n", "6",
        "-i", '{"modules": 1}',
        "-d", json.dumps({"path": corpus_bin("ndlib"),
                          "arguments": "@@"})]) == 0
    report = json.load(open(out))
    mods = report["modules"]
    lib = next(v for k, v in mods.items() if "libnd1" in k)
    main_mod = next(v for k, v in mods.items() if "ndlib" in k)
    assert lib["classification"] == "multi_path_same_file"
    assert lib["nondeterministic_bytes"] > 0
    assert main_mod["classification"] in ("single_path",
                                          "path_per_file")
    assert main_mod["nondeterministic_bytes"] == 0
    # partition-local mask width and placement
    lo, hi = lib["range"]
    assert decode_array(lib["ignore_bytes"]).shape == (hi - lo,)
    # the full-map mask's nonzero bytes all fall inside lib's range
    full = decode_array(report["ignore_bytes"])
    nz = np.flatnonzero(full)
    assert len(nz) and (nz >= lo).all() and (nz < hi).all()


def test_picker_batched_matches_single_exec(corpus_bin, tmp_path):
    """The one-batch seeds x runs matrix must classify identically to
    the per-exec fallback path (deterministic target)."""
    from killerbeez_tpu.tools.picker import collect_traces
    instr = instrumentation_factory("afl", None)
    drv = driver_factory("stdin", json.dumps(
        {"path": corpus_bin("test")}), instr, None)
    seeds = [b"zzzz", b"ABzz"]
    batched = collect_traces(drv, instr, seeds, 3)
    # force the fallback by hiding the host-exec spec
    orig = drv._host_exec_spec
    drv._host_exec_spec = lambda: (_ for _ in ()).throw(
        NotImplementedError())
    single = collect_traces(drv, instr, seeds, 3)
    drv._host_exec_spec = orig
    np.testing.assert_array_equal(batched, single)
    drv.cleanup()
    instr.cleanup()


def test_kb_stats_once_exits_nonzero_without_stats(tmp_path, capsys):
    """Scripts gate on ``kb-stats --once``: a missing or empty
    campaign (no stats.jsonl/fuzzer_stats, or a vacuous snapshot)
    must exit nonzero with a clear message — never an all-zero
    report with exit 0."""
    from killerbeez_tpu.tools.stats_tui import main as stats_main
    # missing path
    assert stats_main([str(tmp_path / "nope"), "--once"]) == 1
    assert "no campaign stats" in capsys.readouterr().err
    # dir exists, no stats files
    d = tmp_path / "out"
    d.mkdir()
    assert stats_main([str(d), "--once"]) == 1
    # stats.jsonl present but vacuous ({} tail line) — the bug this
    # satellite pinned: it used to print an empty report and exit 0
    (d / "stats.jsonl").write_text("{}\n")
    assert stats_main([str(d), "--once"]) == 1
    err = capsys.readouterr().err
    assert "fuzzer_stats" in err and str(d) in err
    # --json mode gates identically
    assert stats_main([str(d), "--once", "--json"]) == 1
    capsys.readouterr()
    # a real snapshot renders and exits 0
    snap = {"t": 10.0, "start_time": 0.0, "elapsed": 10.0,
            "counters": {"execs": 128}, "gauges": {}, "rates": {},
            "derived": {"execs_per_sec": 12.8,
                        "execs_per_sec_ema": 0.0}}
    (d / "stats.jsonl").write_text(json.dumps(snap) + "\n")
    assert stats_main([str(d), "--once"]) == 0
    assert "execs : 128" in capsys.readouterr().out


def test_kb_stats_openmetrics_mode(tmp_path, capsys):
    """``kb-stats --once --openmetrics`` renders the snapshot in the
    OpenMetrics text format (validated by the strict parser the CI
    fleet lane uses) and stage rows gain p50/p99 in the TUI."""
    import sys as _sys
    _sys.path.insert(0, os.path.dirname(__file__))
    from openmetrics_parser import parse_openmetrics, sample_value

    from killerbeez_tpu.telemetry import MetricsRegistry
    from killerbeez_tpu.tools.stats_tui import main as stats_main
    reg = MetricsRegistry()
    reg.count("execs", 2048)
    reg.observe("triage", 0.004)
    reg.observe("triage", 0.012)
    d = tmp_path / "out"
    d.mkdir()
    (d / "stats.jsonl").write_text(
        json.dumps(reg.snapshot()) + "\n")
    assert stats_main([str(d), "--once", "--openmetrics"]) == 0
    fams = parse_openmetrics(capsys.readouterr().out)
    assert sample_value(fams, "kbz_execs", "kbz_execs_total") == 2048
    assert fams["kbz_triage_duration_seconds"]["type"] == "histogram"
    # rendered TUI frame surfaces the stage quantiles
    assert stats_main([str(d), "--once"]) == 0
    out = capsys.readouterr().out
    assert "p50" in out and "p99" in out
    # flag plumbing: --openmetrics needs --once, excludes --json
    assert stats_main([str(d), "--openmetrics"]) == 2
    assert stats_main([str(d), "--once", "--openmetrics",
                       "--json"]) == 2
