"""Stateful protocol fuzzing tier (ISSUE 12): framed session
sequences executed message-by-message on device with state x edge
novelty.

Pins the ISSUE 12 contracts:
  * the framing codec is total and host/device parity-pinned
    (property-tested over random buffers);
  * the in-scan session executor is bit-identical to the host-driven
    per-message reference loop (machine state round-tripping through
    numpy between messages);
  * with feedback off, the -G in-scan sequence path is bit-identical
    to the host-driven stateful loop — findings AND both virgin maps
    — single-chip and dp>1 (the mesh generation scan);
  * the stateful built-ins' deep states are provably single-shot
    unreachable (dataflow + solver certificate) and sequences reach
    them;
  * multipart framed mutation never corrupts message boundaries
    (frame -> mutate -> reframe property test);
  * per-message dictionary groups scope tokens by protocol state;
  * corpus sidecars carry state_sig, kb-corpus renders it, kb-lint
    downgrades session-only dead blocks and flags unreachable
    states, telemetry gauges/events/kb-timeline surface the tier.
"""

import json
import os

import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_NONE
from killerbeez_tpu.fuzzer.loop import Fuzzer
from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.instrumentation.factory import (
    instrumentation_factory,
)
from killerbeez_tpu.models import targets_stateful as ts
from killerbeez_tpu.models.targets import get_target
from killerbeez_tpu.mutators.factory import mutator_factory
from killerbeez_tpu.stateful import (
    StatefulSpec, frame_messages, unframe,
)
from killerbeez_tpu.stateful.framing import (
    compose_manager_seed, parse_frames, parse_frames_np,
)
from killerbeez_tpu.stateful.session import (
    host_reference_session_batch, run_session_batch,
    run_single_session, state_edge_pairs,
)


def _findings(root):
    out = {}
    for kind in ("crashes", "hangs", "new_paths"):
        d = os.path.join(root, kind)
        out[kind] = sorted(
            f for f in (os.listdir(d) if os.path.isdir(d) else [])
            if len(f) == 32)
    return out


SPEC = ts.get_stateful_spec("session_auth")


# ---------------------------------------------------------------------------
# framing codec
# ---------------------------------------------------------------------------

def test_frame_unframe_roundtrip():
    msgs = [b"Lpw", b"QA", b"X"]
    buf = frame_messages(msgs, 4)
    assert unframe(buf, 4) == msgs
    # strict encoder bounds
    with pytest.raises(ValueError):
        frame_messages([], 4)
    with pytest.raises(ValueError):
        frame_messages([b"x"] * 5, 4)
    with pytest.raises(ValueError):
        frame_messages([b"y" * 300], 4)


def test_framing_parse_total_and_host_device_parity():
    """Any byte soup parses, and the device parse agrees with the
    host parse byte-for-byte (the boundary contract both session
    executors share)."""
    rng = np.random.default_rng(42)
    B, L = 128, 40
    bufs = rng.integers(0, 256, size=(B, L), dtype=np.uint8)
    lens = rng.integers(0, L + 1, size=B).astype(np.int32)
    for m_max in (1, 3, 4, 8):
        m_h, off_h, len_h = parse_frames_np(bufs, lens, m_max)
        m_d, off_d, len_d = parse_frames(bufs, lens, m_max)
        assert np.array_equal(m_h, np.asarray(m_d))
        assert np.array_equal(off_h, np.asarray(off_d))
        assert np.array_equal(len_h, np.asarray(len_d))
        # row-wise agreement with the scalar host unframe
        for i in range(0, B, 17):
            msgs = unframe(bytes(bufs[i, :lens[i]]), m_max)
            assert len(msgs) == int(m_h[i])
            for k, m in enumerate(msgs):
                assert len(m) == int(len_h[i, k])


def test_kb_frame_cli(tmp_path):
    from killerbeez_tpu.stateful.framing import main as frame_main
    out = tmp_path / "seq.bin"
    rc = frame_main(["-o", str(out), "-s", "Lpw", "-s", "Q",
                     "--m-max", "4"])
    assert rc == 0
    assert unframe(out.read_bytes(), 4) == [b"Lpw", b"Q"]


# ---------------------------------------------------------------------------
# session executor semantics + host/device parity
# ---------------------------------------------------------------------------

def test_session_runs_seed_sequences():
    for name in ts.stateful_target_names():
        prog = get_target(name)
        spec = ts.get_stateful_spec(name)
        res, pairs = run_single_session(prog, ts.framed_seed(name),
                                        spec)
        assert int(res.status[0]) == FUZZ_NONE
        assert int(res.msgs[0]) == len(ts.seed_sequence(name))
        assert pairs and all(0 <= s < spec.n_states
                             for s, _ in pairs)
        # deep states actually visited by the benign seed
        assert len({s for s, _ in pairs}) >= 2


def test_session_crash_sequences():
    prog = get_target("session_auth")
    seq = frame_messages([b"Lpw", b"QZ", b"QZ"], SPEC.m_max)
    res, _ = run_single_session(prog, seq, SPEC)
    assert int(res.status[0]) == FUZZ_CRASH
    assert int(res.msgs[0]) == 3
    # without login the same queries are denied, no crash
    seq = frame_messages([b"QZ", b"QZ", b"QZ"], SPEC.m_max)
    res, _ = run_single_session(prog, seq, SPEC)
    assert int(res.status[0]) == FUZZ_NONE

    prog = get_target("tcp_like")
    spec = ts.get_stateful_spec("tcp_like")
    seq = frame_messages([b"S\x10", b"A\x11", b"D\xf0!"], spec.m_max)
    res, _ = run_single_session(prog, seq, spec)
    assert int(res.status[0]) == FUZZ_CRASH
    # wrong ack cookie: reset, no establishment, no crash
    seq = frame_messages([b"S\x10", b"A\x77", b"D\xf0!"], spec.m_max)
    res, _ = run_single_session(prog, seq, spec)
    assert int(res.status[0]) == FUZZ_NONE


@pytest.mark.parametrize("name", ["session_auth", "tcp_like"])
def test_session_host_reference_parity(name):
    """The in-scan session executor == the host-driven per-message
    reference loop, field for field, over random byte soup AND
    mutated valid sequences."""
    prog = get_target(name)
    spec = ts.get_stateful_spec(name)
    rng = np.random.default_rng(7)
    B, L = 96, 48
    bufs = rng.integers(0, 256, size=(B, L), dtype=np.uint8)
    seed = ts.framed_seed(name)
    bufs[0, :len(seed)] = np.frombuffer(seed, np.uint8)
    lens = rng.integers(0, L + 1, size=B).astype(np.int32)
    lens[0] = len(seed)
    dev = run_session_batch(prog, bufs, lens, spec)
    host = host_reference_session_batch(prog, bufs, lens, spec)
    for f in dev._fields:
        assert np.array_equal(np.asarray(getattr(dev, f)),
                              np.asarray(getattr(host, f))), f


def test_session_machine_state_carries_across_messages():
    """tcp_like's ACK cookie lives in scratch MEMORY written by the
    SYN handler — correct acks only work because mem persists."""
    prog = get_target("tcp_like")
    spec = ts.get_stateful_spec("tcp_like")
    good = frame_messages([b"S\x30", b"A\x31"], spec.m_max)
    res, _ = run_single_session(prog, good, spec)
    assert int(res.state_final[0]) == 2      # ESTABLISHED
    bad = frame_messages([b"S\x30", b"A\x30"], spec.m_max)
    res, _ = run_single_session(prog, bad, spec)
    assert int(res.state_final[0]) == 0      # reset


# ---------------------------------------------------------------------------
# deep states: the unreachability certificate
# ---------------------------------------------------------------------------

def test_deep_state_certificate():
    """Every deep block is constprop-dead single-shot AND the exact
    solver refutes every deep edge with zero satisfiable paths —
    while the benign seed SEQUENCE lights deep blocks."""
    from killerbeez_tpu.analysis.solver import solve_edge, unknown_kind
    for name in ts.stateful_target_names():
        prog = get_target(name)
        deep = ts.deep_state_blocks(prog)
        assert deep, name
        ef = np.asarray(prog.edge_from)
        et = np.asarray(prog.edge_to)
        for e in ts.deep_state_edges(prog):
            r = solve_edge(prog, (int(ef[e]), int(et[e])))
            assert r.status in ("unsat", "unknown")
            assert r.paths_tried == 0
            if r.status == "unknown":
                assert unknown_kind(r.reason) == "model"
        # the seed sequence executes deep blocks (counts on deep
        # edges are nonzero)
        spec = ts.get_stateful_spec(name)
        res, _ = run_single_session(prog, ts.framed_seed(name), spec)
        counts = np.asarray(res.counts)[0, :-1]
        assert any(counts[e] for e in ts.deep_state_edges(prog)), name
        # ...and the static session half agrees: every deep block is
        # session-reachable (protocol fixpoint)
        from killerbeez_tpu.stateful.protocol import (
            session_reachable_blocks,
        )
        assert set(deep) <= session_reachable_blocks(prog, spec)


def test_single_shot_cannot_reach_deep_slots():
    """The same framed seed executed STATELESSLY (stateful off)
    never lights a collision-free deep slot."""
    prog = get_target("session_auth")
    instr = instrumentation_factory(
        "jit_harness", json.dumps({"target": "session_auth"}))
    mut = mutator_factory("havoc", '{"seed": 3}',
                          ts.framed_seed("session_auth"))
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir="unused", batch_size=128,
                write_findings=False, telemetry=False, feedback=0)
    fz.run(1024)
    slots = np.asarray(prog.edge_slot)
    deep = set(ts.deep_state_edges(prog))
    shallow_slots = {int(slots[e]) for e in range(len(slots))
                     if e not in deep}
    deep_slots = {int(slots[e]) for e in deep} - shallow_slots
    vb = np.asarray(instr.virgin_bits)
    assert deep_slots and all(vb[s] == 0xFF for s in deep_slots)


# ---------------------------------------------------------------------------
# state x edge triage
# ---------------------------------------------------------------------------

def test_state_triage_exact_matches_np_witness():
    from killerbeez_tpu.stateful.coverage import (
        fresh_virgin_state, np_state_triage_exact, state_triage,
        state_triage_exact,
    )
    rng = np.random.default_rng(5)
    B, S, E1 = 32, 4, 9
    se = rng.integers(0, 4, size=(B, S, E1), dtype=np.uint8)
    se[rng.random((B, S, E1)) < 0.8] = 0
    v0 = np.full(S * E1, 0xFF, np.uint8)
    rets_j, v_j = state_triage_exact(np.asarray(v0), np.asarray(se))
    rets_n, v_n = np_state_triage_exact(v0, se)
    assert np.array_equal(np.asarray(rets_j), rets_n)
    assert np.array_equal(np.asarray(v_j), v_n)
    # throughput mode: same final virgin union for distinct lanes,
    # over-reports duplicates but never under-reports
    rets_t, v_t = state_triage(np.asarray(v0), np.asarray(se))
    assert np.array_equal(np.asarray(v_t), v_n)
    assert (np.asarray(rets_t) >= 0).all()


def test_state_novelty_joins_the_verdict():
    """A lane whose CLASSIC map is already known but whose state x
    edge pairs are new still reports novelty (the tier's point)."""
    instr = instrumentation_factory(
        "jit_harness",
        json.dumps({"target": "session_auth", "stateful": 1}))
    # the same single message twice: 'Q' denied from START
    one = frame_messages([b"QA"], SPEC.m_max)
    # then 'L' + 'Q': the SAME query edges now run from AUTHED —
    # classic map saw them (via run 1), the state map did not
    two = frame_messages([b"Lpw", b"QA"], SPEC.m_max)

    def run(buf):
        L = max(len(one), len(two)) + 2
        arr = np.zeros((1, L), np.uint8)
        arr[0, :len(buf)] = np.frombuffer(buf, np.uint8)
        res = instr.run_batch(arr, np.array([len(buf)], np.int32))
        return int(np.asarray(res.new_paths)[0])

    assert run(one) > 0                   # first ever exec: novel
    assert run(one) == 0                  # replay: nothing new
    assert run(two) == 2                  # query-from-AUTHED: the
    # classic query edges exist, but (state=1, edge) pairs are new
    # AND the login edges are classic-new too; replay is quiet
    assert run(two) == 0


def test_state_export_merge_and_layout_guard():
    opts = json.dumps({"target": "tcp_like", "stateful": 1})
    a = instrumentation_factory("jit_harness", opts)
    buf = ts.framed_seed("tcp_like")
    a.enable(buf)
    st = a.get_state()
    assert "virgin_state" in json.loads(st)
    b = instrumentation_factory("jit_harness", opts)
    b.set_state(st)
    assert np.array_equal(np.asarray(a.virgin_state),
                          np.asarray(b.virgin_state))
    c = instrumentation_factory("jit_harness", opts)
    c.merge(st)
    assert np.array_equal(np.asarray(a.virgin_state),
                          np.asarray(c.virgin_state))
    # a mismatched n_states is rejected, not clamped
    d = instrumentation_factory(
        "jit_harness", json.dumps({"target": "tcp_like",
                                   "stateful": 1, "n_states": 4}))
    with pytest.raises(ValueError):
        d.set_state(st)
    # ...and so is a same-SIZED map built under a different state
    # register (different state machine, would alias on AND-fold)
    e = instrumentation_factory(
        "jit_harness", json.dumps({"target": "tcp_like",
                                   "stateful": 1, "state_reg": 6}))
    with pytest.raises(ValueError, match="state spec mismatch"):
        e.set_state(st)
    with pytest.raises(ValueError, match="state spec mismatch"):
        e.merge(st)


# ---------------------------------------------------------------------------
# host loop vs -G parity (single-chip), and dp>1 (mesh scan)
# ---------------------------------------------------------------------------

def _run_campaign(tmp_path, tag, generations, mesh=None, execs=512,
                  batch=64, target="tcp_like"):
    out = str(tmp_path / tag)
    instr = instrumentation_factory(
        "jit_harness", json.dumps({"target": target, "stateful": 1}))
    mut = mutator_factory("havoc", '{"seed": 11}',
                          ts.framed_seed(target))
    if mesh:
        from killerbeez_tpu.parallel import ShardedCampaignDriver
        drv = ShardedCampaignDriver(mesh, instr, mut,
                                    batch_size=batch)
    else:
        drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=out, batch_size=batch, feedback=0,
                generations=generations, telemetry=False)
    fz.run(execs)
    return (_findings(out), np.asarray(instr.virgin_bits),
            np.asarray(instr.virgin_state))


def test_generations_parity_single_chip(tmp_path):
    """-G 4 stateful == the host-driven stateful loop with feedback
    off: findings and BOTH virgin maps bit-identical."""
    fa, vba, vsa = _run_campaign(tmp_path, "host", 0)
    fb, vbb, vsb = _run_campaign(tmp_path, "gen", 4)
    assert fa == fb
    assert fa["new_paths"]                # the run actually found
    assert np.array_equal(vba, vbb)
    assert np.array_equal(vsa, vsb)


def test_generations_parity_mesh_dp2(tmp_path):
    """dp>1: the mesh generation scan == the host-driven mesh loop,
    stateful, feedback off (findings + both maps)."""
    fa, vba, vsa = _run_campaign(tmp_path, "mhost", 0, mesh="2,1")
    fb, vbb, vsb = _run_campaign(tmp_path, "mgen", 4, mesh="2,1")
    assert fa == fb
    assert fa["new_paths"]
    assert np.array_equal(vba, vbb)
    assert np.array_equal(vsa, vsb)


@pytest.mark.slow
def test_generations_parity_mesh_dp4_mp2(tmp_path):
    fa, vba, vsa = _run_campaign(tmp_path, "m42h", 0, mesh="4,2")
    fb, vbb, vsb = _run_campaign(tmp_path, "m42g", 4, mesh="4,2")
    assert fa == fb
    assert np.array_equal(vba, vbb)
    assert np.array_equal(vsa, vsb)


# ---------------------------------------------------------------------------
# multipart framed mutation: boundary round-trip property
# ---------------------------------------------------------------------------

def test_multipart_framed_roundtrip_property():
    """frame -> mutate -> reframe never corrupts message boundaries:
    over random framings and child mutations, every composite
    candidate splits back into exactly the child parts."""
    rng = np.random.default_rng(9)
    # fixed per-message length: every havoc child shares ONE
    # compiled shape, so the property sweep doesn't pay a jit
    # compile per (trial, part)
    for trial, (n_parts, m_max) in enumerate([(1, 3), (3, 7)]):
        msgs = [bytes(rng.integers(0, 256, size=16, dtype=np.uint8))
                for _ in range(n_parts)]
        opts = json.dumps({
            "mutators": ["havoc"] * n_parts,
            "mutator_options": [{"seed": trial * 10 + i}
                                for i in range(n_parts)],
            "framed": 1, "m_max": m_max})
        mut = mutator_factory("manager", opts,
                              compose_manager_seed(msgs))
        for _ in range(8):
            out = mut.mutate()
            parts = unframe(out, m_max)
            assert len(parts) == n_parts
            assert parts == mut.current   # boundaries intact
        bufs, lens = mut.mutate_batch(8)
        bufs, lens = np.asarray(bufs), np.asarray(lens)
        for i in range(8):
            parts = unframe(bytes(bufs[i, :int(lens[i])]), m_max)
            assert len(parts) == n_parts


def test_multipart_accepts_framed_seed():
    """A kb-frame sequence file works directly as a framed manager
    seed (parts split out of the frame header), and framed mode
    reports ONE driver input — the composite is a single buffer, so
    single-input drivers (file) accept it."""
    msgs = ts.seed_sequence("session_auth")
    framed = frame_messages(msgs, 4)
    opts = json.dumps({"mutators": ["havoc"] * len(msgs),
                       "framed": 1, "m_max": 4})
    mut = mutator_factory("manager", opts, framed)
    assert mut.parts == msgs
    assert unframe(mut.mutate(), 4)      # still well-formed
    n_inputs, sizes = mut.get_input_info()
    assert n_inputs == 1 and len(sizes) == 1
    instr = instrumentation_factory(
        "jit_harness",
        json.dumps({"target": "session_auth", "stateful": 1}))
    drv = driver_factory("file", None, instr, mut)  # must not raise
    assert drv.supports_batch
    # unframed manager keeps the multi-part contract (network
    # drivers consume parts)
    mut2 = mutator_factory(
        "manager", json.dumps({"mutators": ["havoc"] * len(msgs)}),
        compose_manager_seed(msgs))
    assert mut2.get_input_info()[0] == len(msgs)


# ---------------------------------------------------------------------------
# per-message dictionary groups
# ---------------------------------------------------------------------------

def test_dictionary_groups_scope_by_state():
    from killerbeez_tpu.stateful.dictionary import (
        extract_dictionary_groups, manager_options_for_target,
    )
    prog = get_target("session_auth")
    msgs = ts.seed_sequence("session_auth")
    groups = extract_dictionary_groups(prog, SPEC, msgs)
    assert len(groups) == len(msgs)
    # the password belongs to the START message only; the query
    # trigger byte 'Z' (a deep-handler constant the single-shot
    # extraction cannot even see) appears exactly in AUTHED groups
    assert b"pw" in groups[0] and b"Z" not in groups[0]
    assert b"Z" in groups[1] and b"pw" not in groups[1]
    # the turnkey manager options build a working mutator
    opts = manager_options_for_target("session_auth")
    mut = mutator_factory("manager", opts,
                          compose_manager_seed(msgs))
    out = mut.mutate()
    assert len(unframe(out, SPEC.m_max)) == len(msgs)


def test_flat_dictionary_misses_deep_tokens():
    """The regression the grouped extraction fixes: the flat
    single-shot pool has no 'Z' at all."""
    from killerbeez_tpu.analysis import extract_dictionary
    toks = extract_dictionary(get_target("session_auth"))
    assert b"Z" not in toks


# ---------------------------------------------------------------------------
# lint: session-only downgrade + unreachable states
# ---------------------------------------------------------------------------

def test_lint_downgrades_session_only_blocks():
    from killerbeez_tpu.analysis import lint_program
    prog = get_target("session_auth")
    plain = lint_program(prog)
    stateful = lint_program(prog, stateful=SPEC)
    dead_plain = [f for f in plain if f.code == "dead-block"]
    assert dead_plain                    # single-shot view: dead
    assert not [f for f in stateful if f.code == "dead-block"]
    only = [f for f in stateful if f.code == "session-only-block"]
    assert {f.data["block"] for f in only} == \
        {f.data["block"] for f in dead_plain}
    assert not [f for f in stateful
                if f.code == "state-unreachable"]


def test_lint_flags_unreachable_state():
    """A guard on a state nothing ever assigns is dead protocol
    surface — the state-unreachable warning."""
    from killerbeez_tpu.analysis import lint_program
    from killerbeez_tpu.models.compiler import Assembler
    a = Assembler("badproto", mem_size=8, max_steps=64)
    a.block()
    a.ldi(1, 0)
    a.ldb(1, 1)
    a.ldi(2, ord("A"))
    a.br("eq", 1, 2, "adv")
    a.ldi(2, 5)                  # guard on state 5...
    a.br("eq", 7, 2, "deep")
    a.jmp("exit")
    a.label("adv")
    a.block()
    a.ldi(7, 1)                  # ...but only state 1 is assigned
    a.halt(0)
    a.label("deep")
    a.block()
    a.halt(9)
    a.label("exit")
    a.block()
    a.halt(0)
    prog = a.build(block_seed=0xBAD)
    spec = StatefulSpec(m_max=4, n_states=8, state_reg=7)
    f = [f for f in lint_program(prog, stateful=spec)
         if f.code == "state-unreachable"]
    assert f and f[0].data["state"] == 5


def test_lint_flags_state_clip():
    from killerbeez_tpu.analysis import lint_program
    from killerbeez_tpu.models.compiler import Assembler
    a = Assembler("clipproto", mem_size=8, max_steps=64)
    a.block()
    a.ldi(7, 12)                 # n_states=8: clips into bucket 7
    a.halt(0)
    prog = a.build(block_seed=0xC11)
    spec = StatefulSpec(m_max=2, n_states=8, state_reg=7)
    f = [f for f in lint_program(prog, stateful=spec)
         if f.code == "state-clip"]
    assert f and f[0].data["value"] == 12


# ---------------------------------------------------------------------------
# corpus sidecars + tools + telemetry
# ---------------------------------------------------------------------------

def test_corpus_state_sig_sidecar_and_tools(tmp_path):
    from killerbeez_tpu.corpus.store import CorpusStore
    from killerbeez_tpu.tools.corpus_tool import (
        render_ls, render_stats,
    )
    out = str(tmp_path / "camp")
    corpus = os.path.join(out, "corpus")
    instr = instrumentation_factory(
        "jit_harness",
        json.dumps({"target": "tcp_like", "stateful": 1}))
    mut = mutator_factory("havoc", '{"seed": 11}',
                          ts.framed_seed("tcp_like"))
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=out, batch_size=64, feedback=8,
                corpus_dir=corpus, telemetry=False)
    fz.run(1024)
    entries = CorpusStore(corpus).load()
    assert entries
    signed = [e for e in entries if e.state_sig]
    assert signed, "session entries must carry state_sig sidecars"
    for e in signed:
        for s, slot in e.state_sig:
            assert 0 <= s < 8 and 0 <= slot < 65536
    # round-trip through the sidecar JSON
    e = signed[0]
    reread = [x for x in CorpusStore(corpus).load()
              if x.md5 == e.md5][0]
    assert reread.state_sig == e.state_sig
    # tools render the state dimension
    assert "states" in render_ls(entries).splitlines()[0]
    stats = render_stats(entries)
    assert "state coverage" in stats and "protocol states" in stats


def test_state_signature_is_pure():
    """The admission signer must not move the virgin maps."""
    instr = instrumentation_factory(
        "jit_harness",
        json.dumps({"target": "session_auth", "stateful": 1}))
    buf = ts.framed_seed("session_auth")
    instr.enable(buf)
    vb0 = np.asarray(instr.virgin_bits).copy()
    vs0 = np.asarray(instr.virgin_state).copy()
    pairs = instr.state_signature(buf)
    assert pairs
    assert np.array_equal(np.asarray(instr.virgin_bits), vb0)
    assert np.array_equal(np.asarray(instr.virgin_state), vs0)


def test_state_gauges_and_events_and_timeline(tmp_path):
    from killerbeez_tpu.telemetry.events import read_events
    from killerbeez_tpu.tools.timeline_tool import sessions_report
    out = str(tmp_path / "camp")
    instr = instrumentation_factory(
        "jit_harness",
        json.dumps({"target": "session_auth", "stateful": 1}))
    mut = mutator_factory("havoc", '{"seed": 2}',
                          ts.framed_seed("session_auth"))
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=out, batch_size=64, feedback=0)
    fz.run(256)
    reg = fz.telemetry.registry
    assert reg.gauges.get("state_cov_pairs", 0) > 0
    assert reg.gauges.get("state_cov_states", 0) >= 2
    evs = list(read_events(os.path.join(out, "events.jsonl")))
    sc = [e for e in evs if e["type"] == "state_cov"]
    assert sc and sc[-1]["pairs"] == reg.gauges["state_cov_pairs"]
    rep = sessions_report(evs)
    assert rep["pairs"] == sc[-1]["pairs"]
    assert rep["states"] >= 2


def test_quarantine_validates_state_sig():
    from killerbeez_tpu.corpus.quarantine import EntryValidator
    from killerbeez_tpu.corpus.store import CorpusEntry
    from killerbeez_tpu.utils.serialization import b64
    v = EntryValidator()
    e = CorpusEntry(b"hello", state_sig=[[1, 5], [0, 9]])
    row = {"md5": e.md5, "content_b64": b64(e.buf),
           "meta": e.meta_dict()}
    ent, why = v.validate(row)
    assert ent is not None, why
    assert ent.state_sig == [[0, 9], [1, 5]]
    row["meta"]["state_sig"] = [["x", 1]]
    ent, why = v.validate(row)
    assert ent is None and why == "schema:state_sig"


# ---------------------------------------------------------------------------
# CLI plumbing
# ---------------------------------------------------------------------------

def test_cli_stateful_flag(tmp_path):
    from killerbeez_tpu.fuzzer.cli import main as cli_main
    seed = tmp_path / "seed.bin"
    seed.write_bytes(ts.framed_seed("session_auth"))
    out = str(tmp_path / "out")
    rc = cli_main(["file", "jit_harness", "havoc",
                   "-i", '{"target": "session_auth"}',
                   "--stateful", "-sf", str(seed), "-n", "256",
                   "-b", "64", "-o", out, "--no-stats"])
    assert rc == 0
    assert _findings(out)["new_paths"]


def test_cli_stateful_requires_jit_harness(tmp_path, capsys):
    from killerbeez_tpu.fuzzer.cli import main as cli_main
    seed = tmp_path / "s"
    seed.write_bytes(b"x")
    rc = cli_main(["file", "return_code", "bit_flip", "--stateful",
                   "-sf", str(seed), "-n", "1",
                   "-d", '{"path": "/bin/true"}'])
    assert rc == 2
    assert "jit_harness" in capsys.readouterr().err


def test_cli_crack_stands_down_stateful(tmp_path, capsys):
    from killerbeez_tpu.fuzzer.cli import main as cli_main
    seed = tmp_path / "s"
    seed.write_bytes(ts.framed_seed("session_auth"))
    rc = cli_main(["file", "jit_harness", "havoc",
                   "-i", '{"target": "session_auth"}',
                   "--stateful", "--crack",
                   "-sf", str(seed), "-n", "64"])
    assert rc == 2
    assert "session" in capsys.readouterr().err


def test_showmap_and_picker_state_sections(tmp_path):
    from killerbeez_tpu.tools.picker import main as picker_main
    seed = tmp_path / "seed.bin"
    seed.write_bytes(ts.framed_seed("tcp_like"))
    rep_path = tmp_path / "picker.json"
    rc = picker_main(["file", "jit_harness", str(seed),
                      "-i", json.dumps({"target": "tcp_like",
                                        "stateful": 1}),
                      "-n", "2", "-o", str(rep_path)])
    assert rc == 0
    rep = json.loads(rep_path.read_text())
    assert "state" in rep
    assert rep["state"]["states_reached"][0] == 0
    assert len(rep["state"]["states_reached"]) >= 2
    assert rep["state"]["pairs"]
