"""Strict pure-python OpenMetrics 1.0 text-format parser — the
conformance oracle for the manager's ``/metrics`` endpoint and
``kb-stats --openmetrics`` (tests + the CI fleet lane import it; it
deliberately has NO dependency on killerbeez_tpu so it can't share a
bug with the renderer it checks).

``parse_openmetrics(text)`` returns ``{family: {"type": ...,
"help": ..., "samples": [(sample_name, labels_dict, value)]}}`` and
raises ``ValueError`` on any violation of the checks below:

  * exposition ends with exactly one ``# EOF`` as its final line
  * every line is a ``# TYPE`` / ``# HELP`` / ``# UNIT`` metadata
    line or a sample
  * metric/label names match the spec charsets
  * one TYPE per family, declared before its samples; families are
    contiguous (no interleaving)
  * samples carry only the suffixes their family's type allows
    (counter -> ``_total``/``_created``; histogram -> ``_bucket`` /
    ``_count`` / ``_sum`` / ``_created``; gauge -> bare name)
  * label syntax: ``name="value"`` with ``\\\\``/``\\"``/``\\n``
    escapes, no duplicate label names, no duplicate name+labelset
    samples within a family
  * values parse as floats; counter totals are >= 0 and not NaN
  * histograms: every labelset has an ``le="+Inf"`` bucket,
    cumulative bucket counts are non-decreasing in ``le`` order, and
    ``_count`` equals the ``+Inf`` bucket
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")

_TYPES = ("counter", "gauge", "histogram", "summary", "unknown",
          "info", "stateset", "gaugehistogram")

_SUFFIXES = {
    "counter": ("_total", "_created"),
    "histogram": ("_bucket", "_count", "_sum", "_created"),
    "gauge": ("",),
    "unknown": ("",),
    "info": ("_info",),
}


def _unescape(v: str) -> str:
    out = []
    i = 0
    while i < len(v):
        ch = v[i]
        if ch == "\\":
            if i + 1 >= len(v):
                raise ValueError(f"dangling escape in {v!r}")
            nxt = v[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:
                raise ValueError(f"bad escape \\{nxt} in {v!r}")
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _parse_labels(body: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    i = 0
    while i < len(body):
        m = re.match(r"([a-zA-Z_][a-zA-Z0-9_]*)=\"", body[i:])
        if not m:
            raise ValueError(f"bad label syntax at {body[i:]!r}")
        name = m.group(1)
        i += m.end()
        j = i
        while j < len(body):
            if body[j] == "\\":
                j += 2
                continue
            if body[j] == '"':
                break
            j += 1
        else:
            raise ValueError(f"unterminated label value in {body!r}")
        value = _unescape(body[i:j])
        if name in labels:
            raise ValueError(f"duplicate label {name!r}")
        labels[name] = value
        i = j + 1
        if i < len(body):
            if body[i] != ",":
                raise ValueError(
                    f"expected ',' between labels in {body!r}")
            i += 1
    return labels


def _parse_value(tok: str) -> float:
    try:
        return float(tok)
    except ValueError:
        raise ValueError(f"bad sample value {tok!r}")


def _split_sample(line: str) -> Tuple[str, Dict[str, str], float]:
    m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)", line)
    if not m:
        raise ValueError(f"bad sample name in {line!r}")
    name = m.group(1)
    rest = line[m.end():]
    labels: Dict[str, str] = {}
    if rest.startswith("{"):
        depth_end = -1
        j = 1
        in_q = False
        while j < len(rest):
            ch = rest[j]
            if in_q:
                if ch == "\\":
                    j += 2
                    continue
                if ch == '"':
                    in_q = False
            elif ch == '"':
                in_q = True
            elif ch == "}":
                depth_end = j
                break
            j += 1
        if depth_end < 0:
            raise ValueError(f"unterminated label set in {line!r}")
        labels = _parse_labels(rest[1:depth_end])
        rest = rest[depth_end + 1:]
    if not rest.startswith(" "):
        raise ValueError(f"missing value separator in {line!r}")
    toks = rest.strip().split(" ")
    if len(toks) not in (1, 2):      # optional timestamp
        raise ValueError(f"trailing garbage in {line!r}")
    return name, labels, _parse_value(toks[0])


def _family_for(name: str, labels: Dict[str, str],
                family: str, ftype: str) -> bool:
    """Does this sample name belong to (family, ftype)?"""
    for suffix in _SUFFIXES.get(ftype, ("",)):
        if name == family + suffix:
            return True
    return False


def _check_histogram(family: str,
                     samples: List[Tuple[str, Dict[str, str], float]]
                     ) -> None:
    by_set: Dict[tuple, Dict[str, object]] = {}
    for name, labels, value in samples:
        key = tuple(sorted((k, v) for k, v in labels.items()
                           if k != "le"))
        slot = by_set.setdefault(key, {"buckets": [], "count": None,
                                       "sum": None})
        if name == family + "_bucket":
            if "le" not in labels:
                raise ValueError(
                    f"{family}: bucket without le label")
            le = labels["le"]
            slot["buckets"].append(
                (math.inf if le == "+Inf" else float(le), value))
        elif name == family + "_count":
            slot["count"] = value
        elif name == family + "_sum":
            slot["sum"] = value
    for key, slot in by_set.items():
        buckets = sorted(slot["buckets"])
        if not buckets or buckets[-1][0] != math.inf:
            raise ValueError(
                f"{family}{dict(key)}: missing le=\"+Inf\" bucket")
        prev = -1.0
        for le, v in buckets:
            if v < prev:
                raise ValueError(
                    f"{family}{dict(key)}: bucket counts decrease "
                    f"at le={le}")
            prev = v
        if slot["count"] is not None \
                and slot["count"] != buckets[-1][1]:
            raise ValueError(
                f"{family}{dict(key)}: _count != +Inf bucket")
        if slot["count"] is not None and slot["sum"] is None:
            raise ValueError(f"{family}{dict(key)}: _count without "
                             f"_sum")


def parse_openmetrics(text: str) -> Dict[str, Dict]:
    if not text.endswith("\n"):
        raise ValueError("exposition must end with a newline")
    lines = text.split("\n")[:-1]
    if not lines or lines[-1] != "# EOF":
        raise ValueError("exposition must end with '# EOF'")
    if "# EOF" in lines[:-1]:
        raise ValueError("'# EOF' before the final line")
    families: Dict[str, Dict] = {}
    closed: set = set()
    current: Optional[str] = None
    for line in lines[:-1]:
        if not line:
            raise ValueError("blank line in exposition")
        if line.startswith("#"):
            m = re.match(r"# (TYPE|HELP|UNIT) "
                         r"([a-zA-Z_:][a-zA-Z0-9_:]*)(?: (.*))?\Z",
                         line)
            if not m:
                raise ValueError(f"bad metadata line {line!r}")
            kind, name, payload = m.groups()
            if name in closed:
                raise ValueError(f"family {name} interleaved")
            if kind == "TYPE":
                slot = families.get(name)
                if slot is not None and slot["type"] is not None:
                    raise ValueError(f"duplicate TYPE for {name}")
                if payload not in _TYPES:
                    raise ValueError(f"unknown type {payload!r}")
                if current is not None and current != name:
                    closed.add(current)
                slot = families.setdefault(
                    name, {"type": None, "help": None,
                           "samples": [], "_seen": set()})
                slot["type"] = payload
                current = name
            else:
                # HELP/UNIT may precede TYPE within the same block
                if current is not None and current != name:
                    closed.add(current)
                current = name
                slot = families.setdefault(
                    name, {"type": None, "help": None,
                           "samples": [], "_seen": set()})
                if kind == "HELP":
                    if slot["help"] is not None:
                        raise ValueError(f"duplicate HELP for {name}")
                    slot["help"] = payload or ""
            continue
        name, labels, value = _split_sample(line)
        if not METRIC_NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        for ln in labels:
            if not LABEL_NAME_RE.match(ln):
                raise ValueError(f"bad label name {ln!r}")
        if current is None:
            raise ValueError(
                f"sample {name!r} before any TYPE line")
        fam = families[current]
        if not _family_for(name, labels, current, fam["type"]):
            raise ValueError(
                f"sample {name!r} does not belong to family "
                f"{current!r} (type {fam['type']})")
        if fam["type"] == "counter":
            if name.endswith("_total") and \
                    (value < 0 or math.isnan(value)):
                raise ValueError(
                    f"counter {name} value {value} invalid")
        key = (name, tuple(sorted(labels.items())))
        if key in fam["_seen"]:
            raise ValueError(f"duplicate sample {key}")
        fam["_seen"].add(key)
        fam["samples"].append((name, labels, value))
    for fname, fam in families.items():
        if fam["type"] is None:
            raise ValueError(f"family {fname} has HELP but no TYPE")
        if fam["type"] == "histogram":
            _check_histogram(fname, fam["samples"])
        fam.pop("_seen", None)
    return families


def sample_value(families: Dict[str, Dict], family: str,
                 sample_name: str,
                 labels: Optional[Dict[str, str]] = None
                 ) -> Optional[float]:
    """Convenience lookup for assertions."""
    fam = families.get(family)
    if fam is None:
        return None
    want = labels or {}
    for name, lab, value in fam["samples"]:
        if name == sample_name and lab == want:
            return value
    return None
