"""Multi-chip tier tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# ~2-4 min of CPU-mesh/interpret-mode work: nightly lane only
pytestmark = pytest.mark.slow

from killerbeez_tpu import FUZZ_CRASH, MAP_SIZE
from killerbeez_tpu.models import targets
from killerbeez_tpu.parallel import (
    make_mesh, make_sharded_fuzz_step, sharded_state_init,
)


def seed_arrays(seed=b"CG\x02\x04\x05\x41xx", L=16):
    buf = np.zeros(L, dtype=np.uint8)
    buf[:len(seed)] = np.frombuffer(seed, dtype=np.uint8)
    return jnp.asarray(buf), jnp.int32(len(seed))


def test_mesh_construction():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    mesh = make_mesh(4, 2)
    assert mesh.shape == {"dp": 4, "mp": 2}
    with pytest.raises(ValueError, match="devices"):
        make_mesh(16, 1)


def run_steps(n_dp, n_mp, n_steps=6, bpd=32):
    prog = targets.get_target("cgc_like")
    mesh = make_mesh(n_dp, n_mp)
    step = make_sharded_fuzz_step(prog, mesh, batch_per_device=bpd,
                                  max_len=16)
    state = sharded_state_init(mesh)
    sb, sl = seed_arrays()
    all_status, all_rets = [], []
    for it in range(n_steps):
        state, statuses, rets, uc, uh, ec, bufs, lens, _c = step(
            state, sb, sl, jnp.int32(it))
        all_status.append(np.asarray(statuses))
        all_rets.append(np.asarray(rets))
    return state, np.concatenate(all_status), np.concatenate(all_rets)


def test_sharded_step_finds_coverage_and_crashes():
    state, statuses, rets = run_steps(4, 2)
    assert (rets > 0).sum() > 0          # found new paths
    assert (statuses == FUZZ_CRASH).sum() > 0  # havoc trips the OOB store
    # virgin map was touched
    vb = np.asarray(state.virgin_bits)
    assert vb.shape == (MAP_SIZE,)
    assert (vb != 0xFF).sum() > 0


def test_virgin_union_is_global_across_dp():
    """After a step, every dp shard holds the same (merged) virgin
    slice — novelty stops being re-reported in later steps."""
    state, _, rets = run_steps(4, 2, n_steps=8)
    per_step = rets.reshape(8, -1)
    # novelty collapses after the first steps (coverage saturates for
    # a fixed seed + havoc)
    assert per_step[-1].sum() <= per_step[0].sum()


def test_mesh_shape_invariance_of_candidates():
    """Candidate streams depend on the global lane id, not the mesh
    shape: total coverage found must match between 8x1 and 4x2 meshes
    with the same global batch."""
    s1, st1, r1 = run_steps(8, 1, n_steps=4, bpd=16)
    s2, st2, r2 = run_steps(4, 2, n_steps=4, bpd=32)
    # same global candidate set => same crash count
    assert (st1 == FUZZ_CRASH).sum() == (st2 == FUZZ_CRASH).sum()
    # and identical final virgin_bits coverage
    np.testing.assert_array_equal(np.asarray(s1.virgin_bits),
                                  np.asarray(s2.virgin_bits))


def test_mp_must_divide_map():
    prog = targets.get_target("test")
    mesh = make_mesh(2, 3)
    with pytest.raises(ValueError, match="divide"):
        make_sharded_fuzz_step(prog, mesh, 8, 16)


def test_sharded_triage_matches_single_chip_reference():
    """The mp-sharded u-space triage must produce EXACTLY the virgin
    maps the single-chip static_triage path produces for the same
    candidate stream — a systematic sharding deviation would otherwise
    pass the mesh-shape-invariance test (which only compares the
    sharded code against itself)."""
    from killerbeez_tpu import FUZZ_HANG, FUZZ_RUNNING
    from killerbeez_tpu.models.vm import _run_batch_impl
    from killerbeez_tpu.ops.mutate_core import havoc_at
    from killerbeez_tpu.ops.static_triage import (
        make_static_maps, static_triage,
    )

    prog = targets.get_target("cgc_like")
    n_steps, bpd, n_dp, n_mp = 4, 16, 4, 2
    B = bpd * n_dp

    # sharded run
    mesh = make_mesh(n_dp, n_mp)
    step = make_sharded_fuzz_step(prog, mesh, batch_per_device=bpd,
                                  max_len=16)
    state = sharded_state_init(mesh, prog.map_size)
    sb, sl = seed_arrays()
    for it in range(n_steps):
        state, *_ = step(state, sb, sl, jnp.int32(it))

    # single-chip reference over the identical candidate stream (the
    # sharded step's global-lane PRNG) with static_triage
    ins = jnp.asarray(prog.instrs)
    tbl = jnp.asarray(prog.edge_table)
    u_np, s_np = make_static_maps(prog.edge_slot)
    u_slots, seg_id = jnp.asarray(u_np), jnp.asarray(s_np)
    vb = vc = vh = jnp.full((prog.map_size,), 0xFF, jnp.uint8)
    base = jax.random.key(0)
    for it in range(n_steps):
        # the sharded step folds the 64-bit counter as [lo, hi] halves
        folded = jax.random.fold_in(
            jax.random.fold_in(base, jnp.uint32(it)), jnp.uint32(0))
        keys = jax.vmap(lambda l: jax.random.fold_in(folded, l))(
            jnp.arange(B, dtype=jnp.uint32))
        bufs, lens = jax.vmap(
            lambda k: havoc_at(sb, sl, k, stack_pow2=4))(keys)
        res = _run_batch_impl(ins, tbl, bufs, lens, prog.mem_size,
                              prog.max_steps, prog.n_edges, False)
        statuses = jnp.where(res.status == FUZZ_RUNNING, FUZZ_HANG,
                             res.status)
        _, _, _, vb, vc, vh = static_triage(
            vb, vc, vh, res.counts, u_slots, seg_id,
            statuses == FUZZ_CRASH, statuses == FUZZ_HANG)

    np.testing.assert_array_equal(np.asarray(state.virgin_bits),
                                  np.asarray(vb))
    np.testing.assert_array_equal(np.asarray(state.virgin_crash),
                                  np.asarray(vc))
    np.testing.assert_array_equal(np.asarray(state.virgin_tmout),
                                  np.asarray(vh))


def test_sharded_step_multimodule_program():
    """Multi-module programs (libtest: 2 x 64KB slot spaces) shard
    over mp like any other map size; library-module novelty must be
    visible in the merged virgin maps."""
    from killerbeez_tpu import MAP_SIZE as ONE_MAP
    prog = targets.get_target("libtest")
    mesh = make_mesh(4, 2)
    step = make_sharded_fuzz_step(prog, mesh, batch_per_device=16,
                                  max_len=8)
    state = sharded_state_init(mesh, prog.map_size)
    sb, sl = seed_arrays(seed=b"LXLX", L=8)
    for it in range(4):
        state, statuses, rets, uc, uh, ec, bufs, lens, _c = step(
            state, sb, sl, jnp.int32(it))
    vb = np.asarray(state.virgin_bits)
    assert vb.shape == (2 * ONE_MAP,)
    # both the main module's and the library module's slot spaces saw
    # coverage (havoc around an 'LX' seed hits both)
    assert (vb[:ONE_MAP] != 0xFF).sum() > 0
    assert (vb[ONE_MAP:] != 0xFF).sum() > 0


def test_sharded_step_unique_crash_flags():
    """uc/uh from the sharded step mirror the single-chip semantics:
    at least one crash lane is flagged unique on the first crashing
    step, and re-running the same step state reports none."""
    prog = targets.get_target("cgc_like")
    mesh = make_mesh(4, 2)
    step = make_sharded_fuzz_step(prog, mesh, batch_per_device=32,
                                  max_len=16)
    state = sharded_state_init(mesh, prog.map_size)
    sb, sl = seed_arrays()
    total_uc = 0
    for it in range(6):
        state, statuses, rets, uc, uh, ec, bufs, lens, _c = step(
            state, sb, sl, jnp.int32(it))
        statuses, uc = np.asarray(statuses), np.asarray(uc)
        assert (~uc | (statuses == FUZZ_CRASH)).all()  # uc => crash
        total_uc += int(uc.sum())
    assert (statuses == FUZZ_CRASH).sum() >= 0
    assert total_uc >= 1
    # replay the last step against the saturated maps: nothing unique
    state2, st2, r2, uc2, uh2, *_ = step(state, sb, sl, jnp.int32(5))
    assert int(np.asarray(uc2).sum()) == 0


def test_cli_mesh_campaign_writes_findings(tmp_path):
    """The PRODUCT multi-chip path: `--mesh dp,mp` drives the sharded
    step through the ordinary Fuzzer loop — findings md5-deduped on
    disk, state dumped in the standard jit_harness format."""
    import json
    import os
    from killerbeez_tpu.fuzzer.cli import main as cli_main

    seed_file = tmp_path / "seed"
    seed_file.write_bytes(b"CG\x02\x04\x05\x41xx")
    out = tmp_path / "out"
    state_file = tmp_path / "state.json"
    rc = cli_main([
        "file", "jit_harness", "havoc", "--mesh", "4,2",
        "-i", '{"target": "cgc_like", "novelty": "throughput"}',
        "-sf", str(seed_file), "-o", str(out),
        "-b", "64", "-n", "256", "-isd", str(state_file),
    ])
    assert rc == 0
    assert os.listdir(out / "new_paths")        # found coverage
    assert os.listdir(out / "crashes")          # havoc trips the bug
    d = json.loads(state_file.read_text())
    assert d["total_execs"] == 256
    assert d["target"] == "cgc_like"
    # telemetry rode along: stats files written, the stream agrees
    # with the mesh exec count, and the per-shard fold surfaced the
    # mesh shape + shard clock as gauges
    from killerbeez_tpu.telemetry import (
        parse_fuzzer_stats, read_latest_snapshot,
    )
    assert int(parse_fuzzer_stats(
        str(out / "fuzzer_stats"))["execs_done"]) == 256
    g = read_latest_snapshot(str(out))["gauges"]
    assert g["mesh_dp"] == 4 and g["mesh_mp"] == 2
    assert g["shard_step"] == 4          # 256 execs / 64-lane quantum
    assert g["lanes_per_shard"] == 16


def test_mesh_campaign_state_roundtrips_through_merger(tmp_path):
    """A campaign state file is a FIRST-CLASS merger input: fold it
    with a single-chip state and load the result back (reference
    merger/merger.c contract, online collectives notwithstanding)."""
    import json
    from killerbeez_tpu.fuzzer.cli import main as cli_main
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.tools.merger import merge_state_files

    seed_file = tmp_path / "seed"
    seed_file.write_bytes(b"CG\x02\x04\x05\x41xx")
    mesh_state = tmp_path / "mesh.json"
    rc = cli_main([
        "file", "jit_harness", "havoc", "--mesh", "4,2",
        "-i", '{"target": "cgc_like", "novelty": "throughput"}',
        "-sf", str(seed_file), "-o", str(tmp_path / "o1"),
        "-b", "64", "-n", "128", "-isd", str(mesh_state),
    ])
    assert rc == 0

    # single-chip state over a DIFFERENT candidate stream
    single = instrumentation_factory(
        "jit_harness", '{"target": "cgc_like"}')
    single.enable(b"CGzzzzzz")
    single_state = tmp_path / "single.json"
    single_state.write_text(single.get_state())

    merged = merge_state_files("jit_harness",
                               '{"target": "cgc_like"}',
                               [str(mesh_state), str(single_state)])
    m = instrumentation_factory("jit_harness",
                                '{"target": "cgc_like"}')
    m.set_state(merged)
    assert m.total_execs == 128 + single.total_execs
    # merged coverage is the union: >= each input's byte count
    a = instrumentation_factory("jit_harness",
                                '{"target": "cgc_like"}')
    a.set_state((tmp_path / "mesh.json").read_text())
    assert m.coverage_bytes() >= a.coverage_bytes()
    assert m.coverage_bytes() >= single.coverage_bytes()


def test_cross_dp_dedup_overreports_never_underreports():
    """VERDICT weak #4 pinned: in-batch dedup is per-dp-shard, so the
    mesh may report MORE new-path lanes than a single chip seeing the
    identical global candidate stream — never fewer, and the virgin
    maps end identical (the AND-fold self-corrects next step)."""
    prog = targets.get_target("cgc_like")
    sb, sl = seed_arrays()
    news = {}
    finals = {}
    for n_dp in (1, 4):
        mesh = make_mesh(n_dp, 1)
        step = make_sharded_fuzz_step(
            prog, mesh, batch_per_device=128 // n_dp, max_len=16)
        state = sharded_state_init(mesh, prog.map_size)
        total = 0
        for it in range(4):
            state, st, rets, *_ = step(state, sb, sl, jnp.int32(it))
            total += int((np.asarray(rets) > 0).sum())
        news[n_dp] = total
        finals[n_dp] = np.asarray(state.virgin_bits)
    assert news[4] >= news[1]
    np.testing.assert_array_equal(finals[1], finals[4])


def test_sharded_pallas_engine_matches_xla():
    """engine="pallas" under shard_map (interpret mode on the CPU
    mesh): same statuses/rets and same final virgin maps as the XLA
    engine for the identical candidate stream."""
    prog = targets.get_target("cgc_like")
    sb, sl = seed_arrays()
    outs = {}
    for engine in ("xla", "pallas"):
        mesh = make_mesh(2, 2)
        step = make_sharded_fuzz_step(
            prog, mesh, batch_per_device=16, max_len=16,
            engine=engine, interpret=True)
        state = sharded_state_init(mesh, prog.map_size)
        sts, rts = [], []
        for it in range(2):
            state, st, rets, *_ = step(state, sb, sl, jnp.int32(it))
            sts.append(np.asarray(st)); rts.append(np.asarray(rets))
        outs[engine] = (np.concatenate(sts), np.concatenate(rts),
                        np.asarray(state.virgin_bits))
    np.testing.assert_array_equal(outs["xla"][0], outs["pallas"][0])
    np.testing.assert_array_equal(outs["xla"][1], outs["pallas"][1])
    np.testing.assert_array_equal(outs["xla"][2], outs["pallas"][2])


def test_sharded_fused_engine_matches_xla():
    """engine="pallas_fused" under shard_map: mutation inside the
    kernel reproduces the havoc_at stream bit-for-bit, so statuses,
    rets, candidates and virgin maps all match the XLA engine."""
    prog = targets.get_target("cgc_like")
    sb, sl = seed_arrays()
    outs = {}
    for engine in ("xla", "pallas_fused"):
        mesh = make_mesh(2, 2)
        step = make_sharded_fuzz_step(
            prog, mesh, batch_per_device=16, max_len=16,
            engine=engine, interpret=True)
        state = sharded_state_init(mesh, prog.map_size)
        state, st, rets, uc, uh, ec, bufs, lens, _c = step(
            state, sb, sl, jnp.int32(0))
        outs[engine] = (np.asarray(st), np.asarray(rets),
                        np.asarray(bufs), np.asarray(lens),
                        np.asarray(state.virgin_bits))
    for i in range(5):
        np.testing.assert_array_equal(outs["xla"][i],
                                      outs["pallas_fused"][i])


def test_counter_folds_all_64_bits():
    """base_it past 2^32 must neither crash (NumPy 2.x uint32
    OverflowError) nor replay an earlier counter's candidate stream:
    2^32 + 7 and 7 share a lo half but differ in hi, so their mutant
    batches must diverge; equal Python-int and device-scalar forms of
    the same sub-2^32 counter must agree."""
    prog = targets.get_target("cgc_like")
    mesh = make_mesh(4, 2)
    step = make_sharded_fuzz_step(prog, mesh, batch_per_device=8,
                                  max_len=16)
    sb, sl = seed_arrays()
    s0 = sharded_state_init(mesh, prog.map_size)

    def bufs_for(it):
        _, *rest = step(s0, sb, sl, it)
        return np.asarray(rest[5])  # candidate buffers [B, L]

    low = bufs_for(7)
    np.testing.assert_array_equal(low, bufs_for(jnp.int32(7)))
    high = bufs_for((1 << 32) + 7)   # would OverflowError pre-fix
    assert (low != high).any(), "hi half of the counter was ignored"


def test_mesh_superbatch_matches_sequential_steps():
    """step.multi (K sharded steps scanned per shard, ICI folds
    inside the scan) must be bit-identical to K sequential sharded
    steps: packed verdicts, candidate tensors, and the final virgin
    state."""
    prog = targets.get_target("cgc_like")
    mesh = make_mesh(4, 2)
    step = make_sharded_fuzz_step(prog, mesh, batch_per_device=8,
                                  max_len=16)
    sb, sl = seed_arrays()
    B, K = 32, 3

    from killerbeez_tpu.instrumentation.base import pack_verdicts
    s = sharded_state_init(mesh, prog.map_size)
    seq = []
    for j in range(K):
        s, st, rets, uc, uh, ec, bufs, lens, _c = step(s, sb, sl,
                                                       j * B)
        pk = pack_verdicts(np.asarray(st), np.asarray(rets),
                           np.asarray(uc), np.asarray(uh))
        seq.append((pk, np.asarray(bufs), np.asarray(lens)))

    s2 = sharded_state_init(mesh, prog.map_size)
    s2, packed, mbufs, mlens, _comp = step.multi(s2, sb, sl, 0, K)
    for j in range(K):
        np.testing.assert_array_equal(seq[j][0],
                                      np.asarray(packed)[j])
        np.testing.assert_array_equal(seq[j][1], np.asarray(mbufs)[j])
        np.testing.assert_array_equal(seq[j][2], np.asarray(mlens)[j])
    np.testing.assert_array_equal(np.asarray(s.virgin_bits),
                                  np.asarray(s2.virgin_bits))
    np.testing.assert_array_equal(np.asarray(s.virgin_crash),
                                  np.asarray(s2.virgin_crash))


def test_cli_mesh_campaign_with_superbatch(tmp_path):
    """--mesh with -K: the mesh K-step accumulation drives the
    ordinary Fuzzer loop end to end (findings on disk, exact exec
    accounting through the state dump)."""
    import json
    import os
    from killerbeez_tpu.fuzzer.cli import main as cli_main

    seed_file = tmp_path / "seed"
    seed_file.write_bytes(b"CG\x02\x04\x05\x41xx")
    out = tmp_path / "out"
    state_file = tmp_path / "state.json"
    rc = cli_main([
        "file", "jit_harness", "havoc", "--mesh", "4,2",
        "-i", '{"target": "cgc_like", "novelty": "throughput"}',
        "-sf", str(seed_file), "-o", str(out),
        "-b", "64", "-n", "512", "-K", "2", "-isd", str(state_file),
    ])
    assert rc == 0
    assert os.listdir(out / "new_paths")
    assert os.listdir(out / "crashes")
    d = json.loads(state_file.read_text())
    assert d["total_execs"] == 512
