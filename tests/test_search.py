"""Gradient-guided search tier (killerbeez_tpu/search/).

Covers the acceptance contract of the subsystem:

  * the distance-returning execute variant is parity-pinned against
    the standard engine when the distance output is ignored
    (bit-exact coverage maps, statuses, steps, path hashes);
  * distances follow Angora's table (0 exactly at satisfaction,
    monotone magnitudes elsewhere, DIST_UNREACHED off-branch);
  * objective extraction finds the deciding branch (and direction)
    of a frontier edge;
  * descent cracks edges the exact solver provably cannot solve
    (imgparse/tlvstack checksum and stack-depth loops), and every
    emitted witness is concretely verified;
  * the soft-KBVM gradient tier is eligible exactly on
    arithmetic-only path slices and proposes distance-reducing
    candidates;
  * the crack-stage escalation caches verdicts in solver.json so a
    resumed campaign never re-descends;
  * the solver's ``unknown`` reasons are pinned by kind on the
    checksum frontier, keeping the search tier's intake set stable.
"""

import json
import os

import numpy as np
import pytest

from killerbeez_tpu.analysis.solver import (
    concrete_run, solve_edge, unknown_kind,
)
from killerbeez_tpu.models import targets, targets_cgc  # noqa: F401
from killerbeez_tpu.models.compiler import Assembler
from killerbeez_tpu.models.vm import (
    CMP_EQ, CMP_GE, DIST_UNREACHED, run_batch, run_batch_distance,
)
from killerbeez_tpu.mutators.base import pack_byte_rows
from killerbeez_tpu.search import (
    descend_edge, edge_objectives, seeds_reaching_block, soft_refine,
    trace_slice,
)


def _imgparse():
    return targets.get_target("imgparse_vm")


def _tlvstack():
    return targets.get_target("tlvstack_vm")


# --------------------------------------------------------------------
# distance engine
# --------------------------------------------------------------------

def test_distance_engine_parity_bit_exact():
    """Ignoring the distance output, the variant must be bit-exact
    with the production engine — coverage maps included."""
    prog = _imgparse()
    rows = [b"QIMGH\x03\x00\x00\x00\x00\x00", b"QIMG", b"\xff" * 16,
            b"", b"QIMGC\x01AA"]
    bufs, lens = pack_byte_rows(rows)
    base = run_batch(prog, bufs, lens, record_stream=False)
    obj = edge_objectives(prog, (13, 14))[0]
    var, dist = run_batch_distance(prog, bufs, lens,
                                   **obj.dist_kwargs())
    for f in ("status", "exit_code", "counts", "steps", "path_hash"):
        np.testing.assert_array_equal(np.asarray(getattr(base, f)),
                                      np.asarray(getattr(var, f)), f)
    assert np.asarray(dist).shape == (len(rows),)


def test_distance_semantics_monotone():
    """eq-objective: |x - y| exactly, 0 at satisfaction, UNREACHED
    for lanes that never sample the branch in-block."""
    a = Assembler("dist_toy")
    a.block()                       # 0
    a.ldi(2, 0)
    a.ldb(1, 2)                     # r1 = input[0]
    a.ldi(2, 42)
    a.br("eq", 1, 2, "win")
    a.block()                       # 1 (miss)
    a.halt(0)
    a.label("win")
    a.block()                       # 2
    a.halt(0)
    prog = a.build()
    obj = [o for o in edge_objectives(prog, (0, 2))][0]
    assert obj.sel == CMP_EQ and obj.want_taken
    rows = [bytes([v]) for v in (0, 40, 41, 42, 44, 255)]
    bufs, lens = pack_byte_rows(rows)
    res, dist = run_batch_distance(prog, bufs, lens,
                                   **obj.dist_kwargs())
    d = np.asarray(dist)
    assert d.tolist() == [42.0, 2.0, 1.0, 0.0, 2.0, 213.0]
    # the satisfied lane actually traversed the edge
    e_idx = [(int(f), int(t)) for f, t in
             zip(prog.edge_from, prog.edge_to)].index((0, 2))
    assert np.asarray(res.counts)[3, e_idx] == 1
    # a lane that never reaches the branch reads UNREACHED
    ge_obj = edge_objectives(prog, (0, 1))[0]
    assert ge_obj.sel != CMP_EQ     # negated: fall-through wanted
    _, d2 = run_batch_distance(prog, np.zeros((1, 8), np.uint8),
                               np.array([0], np.int32),
                               branch_pc=ge_obj.branch_pc,
                               from_idx=5,  # no such source block
                               sel=ge_obj.sel, x_idx=ge_obj.x_idx,
                               y_idx=ge_obj.y_idx)
    assert np.asarray(d2)[0] == np.float32(DIST_UNREACHED)


def test_edge_objectives_checksum_edge():
    """imgparse (13,14) is the H-chunk len==3 guard: one deciding
    branch, fall-through direction, canonicalized to eq."""
    objs = edge_objectives(_imgparse(), (13, 14))
    assert len(objs) == 1
    assert objs[0].sel == CMP_EQ and not objs[0].want_taken
    # guard chains surface every deciding branch, program order
    objs = edge_objectives(_imgparse(), (14, 15))
    assert len(objs) == 4
    # an edge outside the universe has no objectives
    assert edge_objectives(_imgparse(), (0, 999)) == []


# --------------------------------------------------------------------
# descent
# --------------------------------------------------------------------

def test_descend_cracks_imgparse_checksum_edge():
    """(13,14) is solver-unknown (checksum loop); descent must crack
    it from the solver's own witness for the dispatch edge."""
    prog = _imgparse()
    assert solve_edge(prog, (13, 14)).status == "unknown"
    seed = solve_edge(prog, (11, 13)).input
    assert seed is not None
    res = descend_edge(prog, (13, 14), [seed], lanes=128, budget=12)
    assert res.status == "descended"
    # the honesty contract, re-checked here independently
    assert (13, 14) in concrete_run(prog, res.input).edges
    assert res.steps <= 12 and res.evals >= res.steps * 64


def test_descend_cracks_tlvstack_stack_depth_edge():
    """tlvstack (28,29) (op_swap needs sp >= 2) requires INSERTING
    push commands before the swap — the structural moves' regression
    case: no fixed-position byte move can add a command record."""
    prog = _tlvstack()
    assert solve_edge(prog, (28, 29)).status == "unknown"
    # seed with the witness of the edge INTO the swap handler's head
    preds = [(int(f), int(t)) for f, t in
             zip(prog.edge_from, prog.edge_to) if int(t) == 28]
    seeds = [r.input for e in preds
             if (r := solve_edge(prog, e)).input]
    assert seeds
    se = seeds_reaching_block(prog, seeds, 28) or seeds
    res = descend_edge(prog, (28, 29), se, lanes=256, budget=24)
    assert res.status == "descended"
    assert (28, 29) in concrete_run(prog, res.input).edges


def test_descend_exhausted_is_honest():
    """An impossible intake (source block never reaches the target's
    region with usable seeds) exhausts with no witness rather than
    guessing."""
    a = Assembler("never")
    a.block()                       # 0
    a.ldi(2, 0)
    a.ldb(1, 2)
    a.ldi(2, 1)
    a.alu("mul", 3, 1, 2)
    a.ldi(2, 256)                   # a byte can never be 256
    a.br("eq", 3, 2, "win")
    a.block()                       # 1
    a.halt(0)
    a.label("win")
    a.block()                       # 2
    a.halt(0)
    prog = a.build()
    res = descend_edge(prog, (0, 2), [b"\x00"], lanes=64, budget=4)
    assert res.status == "exhausted"
    assert res.input is None
    assert res.steps == 4
    assert res.best_dist > 0


def test_descend_spans_on_descent_lane():
    """kb-timeline contract: every descent dispatch is a span on the
    dedicated ``descent`` lane."""
    from killerbeez_tpu.telemetry.trace import TraceRecorder
    prog = _imgparse()
    seed = solve_edge(prog, (11, 13)).input
    tr = TraceRecorder(max_events=4096)
    descend_edge(prog, (13, 14), [seed], lanes=64, budget=4, trace=tr)
    chrome = tr.to_chrome()
    lane_tid = tr.lane_id("descent")
    spans = [e for e in chrome["traceEvents"]
             if e.get("name") == "descend_batch"
             and e.get("tid") == lane_tid and e.get("ph") == "B"]
    assert spans, "descent batches must land on the descent lane"
    assert all("edge" in s.get("args", {}) for s in spans)


def test_seeds_reaching_block_filter():
    prog = _imgparse()
    seed = solve_edge(prog, (11, 13)).input
    assert seeds_reaching_block(prog, [seed, b"zzz"], 13) == [seed]
    # entry pseudo-block accepts everything
    assert len(seeds_reaching_block(prog, [seed, b"zzz"], -1)) == 2


# --------------------------------------------------------------------
# soft-KBVM gradient tier
# --------------------------------------------------------------------

def _arith_prog():
    """r3 = 3*input[0] + input[1]; branch eq r3, 200."""
    a = Assembler("arith")
    a.block()                       # 0
    a.ldi(2, 0)
    a.ldb(1, 2)                     # r1 = b0
    a.ldi(2, 3)
    a.alu("mul", 3, 1, 2)           # r3 = 3*b0
    a.ldi(2, 1)
    a.ldb(1, 2)                     # r1 = b1
    a.alu("add", 3, 3, 1)           # r3 += b1
    a.ldi(2, 200)
    a.br("eq", 3, 2, "win")
    a.block()                       # 1
    a.halt(0)
    a.label("win")
    a.block()                       # 2
    a.halt(0)
    return a.build()


def test_soft_slice_eligibility():
    prog = _arith_prog()
    obj = edge_objectives(prog, (0, 2))[0]
    sl = trace_slice(prog, b"\x00\x00", obj)
    assert sl.eligible
    # bit ops poison eligibility
    a = Assembler("bitop")
    a.block()
    a.ldi(2, 0)
    a.ldb(1, 2)
    a.ldi(2, 255)
    a.alu("and", 3, 1, 2)
    a.ldi(2, 77)
    a.br("eq", 3, 2, "win")
    a.block()
    a.halt(0)
    a.label("win")
    a.block()
    a.halt(0)
    bprog = a.build()
    bobj = edge_objectives(bprog, (0, 2))[0]
    bsl = trace_slice(bprog, b"\x00\x00", bobj)
    assert not bsl.eligible and "ALU" in bsl.reason
    assert soft_refine(bprog, b"\x00\x00", bobj) == []


def test_soft_refine_descends_distance():
    """One gradient step must propose candidates strictly closer to
    satisfying 3*b0 + b1 == 200 than the start point."""
    prog = _arith_prog()
    obj = edge_objectives(prog, (0, 2))[0]
    start = b"\x00\x00"

    def gap(buf):
        return abs(3 * buf[0] + buf[1] - 200)

    cands = soft_refine(prog, start, obj)
    assert cands
    assert min(gap(c) for c in cands) < gap(start)


def test_soft_tier_inside_descent():
    """The full engine cracks the arithmetic target and reports the
    soft tier's participation."""
    prog = _arith_prog()
    res = descend_edge(prog, (0, 2), [b"\x00\x00"], lanes=64,
                       budget=16)
    assert res.status == "descended"
    assert 3 * res.input[0] + res.input[1] == 200


# --------------------------------------------------------------------
# solver intake fixtures (satellite): the unknown REASONS are pinned
# --------------------------------------------------------------------

def test_unknown_kind_taxonomy():
    assert unknown_kind("path-search budget exhausted (7 expansions)") \
        == "budget"
    assert unknown_kind("no satisfiable path within the visit/step "
                        "caps (loop-carried state beyond 2 passes is "
                        "not modeled)") == "visit-cap"
    assert unknown_kind("no satisfiable path under the bounded input "
                        "model (reads forced in-bounds, length capped "
                        "at 64 — raise max_len or accept unknown)") \
        == "model"
    assert unknown_kind("anything else") == "other"


# the search tier's intake on the checksum universes: these edges ARE
# unknown, for the visit-cap reason, at default budgets.  A solver
# improvement that flips one to solved must update this fixture (and
# the kb-descend floors) explicitly rather than silently reshaping
# the frontier.
_IMGPARSE_CHECKSUM_EDGES = [(13, 14), (14, 15), (16, 17), (24, 24),
                            (33, 31)]
_TLVSTACK_DEPTH_EDGES = [(12, 13), (28, 29), (30, 31)]


@pytest.mark.parametrize("edge", _IMGPARSE_CHECKSUM_EDGES)
def test_imgparse_intake_reason_pinned(edge):
    r = solve_edge(_imgparse(), edge)
    assert r.status == "unknown"
    assert unknown_kind(r.reason) == "visit-cap"


@pytest.mark.parametrize("edge", _TLVSTACK_DEPTH_EDGES)
def test_tlvstack_intake_reason_pinned(edge):
    r = solve_edge(_tlvstack(), edge)
    assert r.status == "unknown"
    assert unknown_kind(r.reason) == "visit-cap"


def test_budget_kind_surfaces_when_budget_tiny():
    r = solve_edge(_imgparse(), (13, 14), budget=50)
    assert r.status == "unknown"
    assert unknown_kind(r.reason) == "budget"


# --------------------------------------------------------------------
# crack-stage escalation (fuzzer/crack.py --descend)
# --------------------------------------------------------------------

@pytest.fixture(scope="module")
def blind_campaign(tmp_path_factory):
    """ONE escalated blind campaign shared by the e2e assertions —
    small enough for CI but long enough to plateau: the crack trigger
    pads its window by PIPELINE_DEPTH batches, so n must comfortably
    exceed (plateau + depth) * batch."""
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory
    tmp_path = tmp_path_factory.mktemp("blind")
    instr = instrumentation_factory(
        "jit_harness", json.dumps({"target": "imgparse_vm",
                                   "novelty": "throughput"}))
    mut = mutator_factory("havoc", '{"seed": 11}', b"\x00" * 8)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "out"),
                batch_size=64, write_findings=False,
                corpus_dir=str(tmp_path / "corpus"))
    fz.cracker = BranchCracker(instr.program,
                               plateau_batches=2, store=fz.store,
                               descend=16, descend_lanes=256)
    fz.run(8192)
    return fz, instr.program


def test_cracker_escalates_and_caches(blind_campaign):
    """End-to-end: a blind campaign with --descend must record
    descent attempts, produce at least one verified witness on the
    checksum frontier, inject it, and cache the verdict (including
    exhausted ones) in the solver.json sidecar so the next crack —
    and a --resume — never re-descends."""
    fz, prog = blind_campaign
    reg = fz.telemetry.registry
    assert reg.counters.get("search_attempts", 0) >= 1
    assert reg.counters.get("search_descended", 0) >= 1
    searched = {k: v for k, v in fz.cracker.cache.items()
                if "search" in v}
    assert searched
    for v in searched.values():
        assert v["search"]["status"] in ("descended", "exhausted")
        if v.get("status") == "descended":
            # the cached witness really traverses its edge
            f, t = (int(x) for x in
                    next(k for k, vv in fz.cracker.cache.items()
                         if vv is v).split(":"))
            buf = bytes.fromhex(v["input_hex"])
            assert (f, t) in concrete_run(prog, buf).edges
    # sidecar persisted
    disk = fz.store.load_solver_cache()
    assert any("search" in v for v in disk.values())

    # a fresh cracker over the same store re-attempts NOTHING
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    c2 = BranchCracker(prog, plateau_batches=2, store=fz.store,
                       descend=16, descend_lanes=256)
    attempted = [e for e in c2.edges
                 if "search" in (c2.cache.get(f"{e[0]}:{e[1]}") or {})]
    before = reg.counters.get("search_attempts", 0)
    instr = fz.driver.instrumentation
    n = c2._descend_frontier(fz, attempted)
    assert n == 0
    assert reg.counters.get("search_attempts", 0) == before


def test_exhausted_verdicts_persist_without_fresh_solves(blind_campaign,
                                                         tmp_path):
    """Regression: a crack where every edge already has a cached
    solve verdict (fresh == []) but descents run must still persist
    the cache — exhausted search verdicts included — or --resume
    re-descends them."""
    from killerbeez_tpu.corpus.store import CorpusStore
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    fz, prog = blind_campaign
    store = CorpusStore(str(tmp_path / "c2"))
    c = BranchCracker(prog, plateau_batches=2, store=store,
                      descend=2, descend_lanes=64)
    # pre-cache every edge as solver-unknown: no fresh solves happen
    for e in c.edges:
        c.cache[f"{e[0]}:{e[1]}"] = {"status": "unknown", "reason": "x"}
    store.save_solver_cache(c.cache)
    c.crack(fz)
    disk = store.load_solver_cache()
    searched = [k for k, v in disk.items() if "search" in v]
    assert searched, "attempted-but-exhausted verdicts must persist"


def test_descended_witnesses_inject_through_main_path(blind_campaign):
    """Coverage beyond the solver ceiling: with escalation on, the
    campaign's virgin map must light static edges the exact solver
    cannot solve."""
    fz, prog = blind_campaign
    instr = fz.driver.instrumentation
    vb = np.asarray(instr.virgin_bits)
    covered = set(np.flatnonzero(vb != 0xFF).tolist())
    slot_of = {(int(f), int(t)): int(s) for f, t, s in
               zip(prog.edge_from, prog.edge_to, prog.edge_slot)}
    descended = [tuple(int(x) for x in k.split(":"))
                 for k, v in fz.cracker.cache.items()
                 if v.get("status") == "descended"]
    assert descended
    assert any(slot_of[e] in covered for e in descended)
