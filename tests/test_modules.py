"""Per-module coverage (VERDICT missing #1): modules are block-index
ranges with their own 64KB slot space and virgin state — the KBVM
analogue of the reference's per-library target_module_t list
(dynamorio_instrumentation.h:27-41).

Acceptance: novelty in module B is detected after module A saturates.
"""

import numpy as np
import pytest

from killerbeez_tpu import MAP_SIZE
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.models import targets
from killerbeez_tpu.models.compiler import Assembler


def test_libtest_has_two_modules():
    prog = targets.get_target("libtest")
    assert prog.module_names == ("target", "libtest1")
    assert prog.map_size == 2 * MAP_SIZE
    # library edges live in the second module's slot space
    lib_lo = prog.modules[1][1]
    for e in range(prog.n_edges):
        to_blk = int(prog.edge_to[e])
        slot = int(prog.edge_slot[e])
        if to_blk >= lib_lo:
            assert MAP_SIZE <= slot < 2 * MAP_SIZE
        else:
            assert 0 <= slot < MAP_SIZE


def test_module_b_novelty_after_a_saturated():
    instr = instrumentation_factory("jit_harness",
                                    '{"target": "libtest"}')
    # saturate the main module: every non-library path
    for data in (b"QQ", b"ZZ", b"Q", b""):
        instr.enable(data or b"\x00")
    cov = instr.module_coverage_bytes()
    assert cov["target"] > 0
    assert cov["libtest1"] == 0
    instr.enable(b"QQ")
    assert instr.is_new_path() == 0          # module A is saturated
    # library path: novelty must be detected in module B
    instr.enable(b"LY")
    assert instr.is_new_path() > 0
    cov = instr.module_coverage_bytes()
    assert cov["libtest1"] > 0
    # deeper library path still novel; repeated run is not
    instr.enable(b"LX")
    assert instr.is_new_path() > 0
    instr.enable(b"LX")
    assert instr.is_new_path() == 0


def test_get_module_info_and_module_edges():
    instr = instrumentation_factory(
        "jit_harness", '{"target": "libtest", "edges": 1}')
    assert instr.get_module_info() == ["target", "libtest1"]
    instr.enable(b"LX")
    lib_edges = instr.get_module_edges("libtest1")
    main_edges = instr.get_module_edges("target")
    assert lib_edges and main_edges
    # module-local slot numbers stay inside one 64KB map
    assert all(0 <= s < MAP_SIZE for s, _ in lib_edges)
    instr.enable(b"QQ")
    assert instr.get_module_edges("libtest1") == []


def test_state_roundtrip_multimodule():
    instr = instrumentation_factory("jit_harness",
                                    '{"target": "libtest"}')
    instr.enable(b"LX")
    state = instr.get_state()
    other = instrumentation_factory("jit_harness",
                                    '{"target": "libtest"}')
    other.set_state(state)
    np.testing.assert_array_equal(np.asarray(other.virgin_bits),
                                  np.asarray(instr.virgin_bits))
    # merge is an AND-fold per byte across the full multi-module map
    third = instrumentation_factory("jit_harness",
                                    '{"target": "libtest"}')
    third.enable(b"QQ")
    third.merge(state)
    cov = third.module_coverage_bytes()
    assert cov["libtest1"] > 0 and cov["target"] > 0


def test_empty_module_rejected():
    a = Assembler("x")
    a.module("m1")
    with pytest.raises(ValueError):
        a.module("m2")


def test_single_module_default_unchanged():
    prog = targets.get_target("test")
    assert prog.module_names == ("target",)
    assert prog.map_size == MAP_SIZE


# ---------------- native tier ----------------

def test_native_per_module_partitions(corpus_bin, monkeypatch):
    """Native targets under KB_MODULES=1: the kb-cc-built shared
    library claims its own map partition; novelty in the library is
    visible with the main module saturated."""
    monkeypatch.setenv("KB_MODULES", "1")
    from killerbeez_tpu.native.exec_backend import (
        ExecTarget, KB_MOD_SIZE,
    )
    with ExecTarget([corpus_bin("libtest")], use_stdin=True,
                    use_forkserver=True, coverage=True) as t:
        t.clear_trace()
        t.run(b"zz")
        names = t.module_table()
        assert "libtest1.so" in names and "libtest" in names
        lib_idx = names.index("libtest1.so")
        m_plain = t.trace_bits().copy()
        t.clear_trace()
        t.run(b"LX")
        m_lib = t.trace_bits().copy()
    lib_lo, lib_hi = lib_idx * KB_MOD_SIZE, (lib_idx + 1) * KB_MOD_SIZE
    assert (m_plain[lib_lo:lib_hi] != 0).sum() == 0
    assert (m_lib[lib_lo:lib_hi] != 0).sum() > 0


def test_native_afl_module_novelty(corpus_bin):
    """afl instrumentation with modules:1 — module B novelty after A
    saturates (the VERDICT acceptance shape, native tier)."""
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    instr = instrumentation_factory("afl", '{"modules": 1, "edges": 1}')
    try:
        instr.prepare_host(corpus_bin("libtest"), use_stdin=True)
        for data in (b"zz", b"M", b"x"):
            instr.enable(data, cmd_line=corpus_bin("libtest"))
        instr.enable(b"yy", cmd_line=corpus_bin("libtest"))
        assert instr.is_new_path() == 0        # main module saturated
        instr.enable(b"LZ", cmd_line=corpus_bin("libtest"))
        assert instr.is_new_path() > 0         # library novelty
        names = instr.get_module_info()
        assert "libtest1.so" in names
        cov = instr.module_coverage_bytes()
        assert cov["libtest1.so"] > 0
        lib_edges = instr.get_module_edges("libtest1.so")
        assert lib_edges
    finally:
        instr.cleanup()
    import os
    assert "KB_MODULES" not in os.environ
