"""The generated target zoo (models/zoo.py + kb-zoo).

Pins the zoo's contracts: every family instance certifies (lint
clean, benign seed misses the deep edge and exits clean, witness
crashes THROUGH it under exact concrete semantics), generation is
deterministic, instances resolve through the ordinary target
registry under ``zoo:`` names, bad names fail loudly, and the
kb-zoo CLI round-trips list / certify / generate.
"""

import json
import os

import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_NONE
from killerbeez_tpu.analysis.solver import concrete_run
from killerbeez_tpu.models.targets import get_target
from killerbeez_tpu.models.zoo import (
    GATED_NAMES, build_zoo, certify_zoo, parse_zoo_name, zoo_families,
    zoo_name,
)
from killerbeez_tpu.tools import zoo_tool

ALL_INSTANCES = list(GATED_NAMES) + [
    "zoo:tlv:depth=1,bug=0",
    "zoo:tlv:depth=4,bug=2",
    "zoo:chain:width=1,bug=0",
    "zoo:chain:width=6,bug=4",
    "zoo:cksum:style=xor,bug=0",
]


# ---------------------------------------------------------------------------
# certification over the parameter space
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ALL_INSTANCES)
def test_zoo_instance_certifies(name):
    r = certify_zoo(name)
    assert r["certified"], r
    assert r["seed_benign"] and r["witness_crashes"]
    assert not r["lint_errors"]


@pytest.mark.parametrize("name", GATED_NAMES)
def test_zoo_deep_edge_is_crash_coincident(name):
    """The planted bug's verdict branch IS the crash: the witness
    trace crosses the deep edge and dies, the benign seed does
    neither — the property the bench gate's deep-slot metric reads."""
    t = build_zoo(name)
    seed_tr = concrete_run(t.program, t.seed)
    crash_tr = concrete_run(t.program, t.crash)
    assert seed_tr.status == FUZZ_NONE
    assert t.deep_edge not in seed_tr.edges
    assert crash_tr.status == FUZZ_CRASH
    assert t.deep_edge in crash_tr.edges


@pytest.mark.parametrize("name", GATED_NAMES)
def test_zoo_deep_edge_has_collision_free_slot(name):
    """The gate metric is honest only if the deep edge owns its AFL
    slot — pinned per gated instance."""
    t = build_zoo(name)
    ef = np.asarray(t.program.edge_from)
    et = np.asarray(t.program.edge_to)
    slots = np.asarray(t.program.edge_slot)
    deep = [e for e in range(len(et))
            if (int(ef[e]), int(et[e])) == t.deep_edge]
    assert deep
    other = {int(slots[e]) for e in range(len(et)) if e not in deep}
    assert {int(slots[e]) for e in deep} - other


def test_zoo_generation_deterministic():
    a = build_zoo("zoo:tlv:depth=2,bug=1")
    b = build_zoo("zoo:tlv:depth=2,bug=1")
    assert np.array_equal(np.asarray(a.program.instrs),
                          np.asarray(b.program.instrs))
    assert a.seed == b.seed and a.crash == b.crash
    assert a.grammar.to_json() == b.grammar.to_json()


def test_zoo_grammar_carries_trigger_token():
    """The family grammar's command alphabet includes the trigger —
    that is the whole crack mechanism (one token substitution)."""
    from killerbeez_tpu.models.zoo import _tokens
    for name in GATED_NAMES:
        t = build_zoo(name)
        _, trigger = _tokens(t.params["bug"])
        alphas = [f for r in t.grammar.rules.values()
                  for f in r.fields if f.kind == "token"]
        assert alphas and any(trigger in a.alphabet for a in alphas)
        assert trigger in t.crash and trigger not in t.seed


# ---------------------------------------------------------------------------
# names and registry resolution
# ---------------------------------------------------------------------------


def test_zoo_names_roundtrip_and_defaults():
    fam, params = parse_zoo_name("zoo:tlv")
    assert fam == "tlv" and params == zoo_families()["tlv"]
    assert parse_zoo_name(zoo_name(fam, params))[1] == params
    fam, params = parse_zoo_name("zoo:cksum:bug=2")
    assert params["style"] == "sum" and params["bug"] == 2


@pytest.mark.parametrize("bad,msg", [
    ("tlv:depth=2", "not a zoo target"),
    ("zoo:nosuch", "unknown zoo family"),
    ("zoo:tlv:nope=1", "bad zoo parameter"),
    ("zoo:tlv:depth=99", "out of range"),
    ("zoo:cksum:style=crc", "sum or xor"),
])
def test_zoo_bad_names_fail_loudly(bad, msg):
    with pytest.raises(ValueError, match=msg):
        build_zoo(bad) if bad.startswith("zoo:") else \
            parse_zoo_name(bad)


def test_zoo_resolves_through_target_registry():
    prog = get_target("zoo:chain:width=3,bug=1")
    assert prog.name.startswith("zoo_chain")
    with pytest.raises(ValueError, match="unknown zoo family"):
        get_target("zoo:bogus")


# ---------------------------------------------------------------------------
# kb-zoo CLI
# ---------------------------------------------------------------------------


def test_kb_zoo_list(capsys):
    assert zoo_tool.main(["list"]) == 0
    out = capsys.readouterr().out
    for fam in zoo_families():
        assert fam in out
    for n in GATED_NAMES:
        assert n in out


def test_kb_zoo_certify_json(capsys):
    assert zoo_tool.main(["certify", "--json",
                          "zoo:tlv:depth=1,bug=0"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["certified"]
    assert doc["targets"][0]["name"] == "zoo:tlv:bug=0,depth=1"


def test_kb_zoo_generate_bundle(tmp_path, capsys):
    out = str(tmp_path / "bundle")
    assert zoo_tool.main(["generate", "zoo:cksum:style=sum,bug=1",
                          "--out", out]) == 0
    for f in ("program.npz", "seed", "crash", "grammar.json",
              "certificate.json"):
        assert os.path.exists(os.path.join(out, f))
    with open(os.path.join(out, "certificate.json")) as f:
        assert json.load(f)["certified"]
    # the npz round-trips through the ordinary program_file loader
    from killerbeez_tpu.models.targets import load_program_from_options
    prog = load_program_from_options(
        {"program_file": os.path.join(out, "program.npz")}, "x")
    with open(os.path.join(out, "crash"), "rb") as f:
        crash = f.read()
    assert concrete_run(prog, crash).status == FUZZ_CRASH
