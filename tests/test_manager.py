"""Manager tier tests (manager/: DB, REST API, work queue, worker +
assimilator) — reference SURVEY §2.8/§3.5 lifecycle: job create with
config resolution -> reproducible cmdline -> worker claim -> fuzz ->
assimilate findings -> results query; plus the minimize endpoint
(greedy edge cover over tracer_info, reference minimizer_test parity)
and stale-claim requeue (BOINC workunit retry semantics).
"""

import base64
import json
import urllib.request

import pytest

from killerbeez_tpu.manager import ManagerDB, ManagerServer, format_cmdline
from killerbeez_tpu.manager.worker import work_loop


@pytest.fixture
def server():
    s = ManagerServer(port=0)  # ephemeral port
    s.start()
    yield s
    s.stop()


def req(server, path, payload=None, method=None):
    url = f"http://127.0.0.1:{server.port}{path}"
    method = method or ("POST" if payload is not None else "GET")
    data = json.dumps(payload).encode() if payload is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers={"Content-Type":
                                        "application/json"})
    with urllib.request.urlopen(r, timeout=10) as resp:
        body = resp.read()
        if resp.status == 204 or not body:
            return resp.status, None
        return resp.status, json.loads(body)


def test_db_config_resolution_per_target_beats_global():
    db = ManagerDB()
    tid = db.create_target("t1")
    db.set_config("mutator_opts_bit_flip", '{"num_bits": 1}')
    db.set_config("mutator_opts_bit_flip", '{"num_bits": 4}', tid)
    jid = db.create_job(tid, "file", "afl", "bit_flip")
    assert db.get_job(jid)["mutator_opts"] == '{"num_bits": 4}'
    db2_tid = db.create_target("t2")
    jid2 = db.create_job(db2_tid, "file", "afl", "bit_flip")
    assert db.get_job(jid2)["mutator_opts"] == '{"num_bits": 1}'


def test_format_cmdline_sh_escaping():
    job = {"driver": "file", "instrumentation": "afl",
           "mutator": "bit_flip", "iterations": 50,
           "seed_file": "seed with space.bin",
           "driver_opts": '{"path": "t"}'}
    cmd = format_cmdline(job)
    assert cmd.startswith("python -m killerbeez_tpu.fuzzer "
                          "file afl bit_flip")
    assert "'seed with space.bin'" in cmd
    assert "-n 50" in cmd
    assert "'{\"path\": \"t\"}'" in cmd


def test_rest_target_config_job_roundtrip(server):
    code, t = req(server, "/api/target", {"name": "tgt"})
    assert code == 201
    code, _ = req(server, "/api/config",
                  {"name": "driver_opts_file",
                   "value": '{"path": "x"}', "target_id": t["id"]})
    assert code == 201
    code, job = req(server, "/api/job",
                    {"target_id": t["id"], "driver": "file",
                     "instrumentation": "afl", "mutator": "havoc",
                     "iterations": 10, "seed_file": "s.bin"})
    assert code == 201 and "cmdline" in job
    code, full = req(server, f"/api/job/{job['id']}")
    assert code == 200
    assert full["driver_opts"] == '{"path": "x"}'  # config resolved
    code, jobs = req(server, "/api/job?status=pending")
    assert code == 200 and len(jobs) == 1


def test_rest_file_roundtrip(server):
    payload = b"\x00\x01repro"
    code, f = req(server, "/api/file",
                  {"name": "r", "content_b64":
                   base64.b64encode(payload).decode()})
    assert code == 201
    url = f"http://127.0.0.1:{server.port}/api/file/{f['id']}"
    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.read() == payload


def test_rest_minimize_greedy_cover(server):
    code, t = req(server, "/api/target", {"name": "tgt"})
    for name, edges in (("a", [1, 2, 3]), ("b", [2]), ("c", [9])):
        code, _ = req(server, "/api/tracer_info",
                      {"target_id": t["id"], "input_file": name,
                       "edges": edges})
        assert code == 201
    code, out = req(server, "/api/minimize", {"target_id": t["id"]})
    assert code == 200
    assert set(out["working_set"]) == {"a", "c"}  # b ⊂ a dropped


def test_work_claim_empty_queue_is_204(server):
    code, body = req(server, "/api/work/claim", {"worker": "w"})
    assert code == 204 and body is None


def test_requeue_stale_jobs():
    db = ManagerDB()
    tid = db.create_target("t")
    db.create_job(tid, "file", "afl", "nop")
    job = db.claim_job("w1")
    assert job is not None and db.claim_job("w2") is None
    assert db.requeue_stale_jobs(older_than_s=0.0) == 1
    assert db.claim_job("w2") is not None


def test_end_to_end_job_lifecycle(server, corpus_bin, tmp_path):
    """Full fleet loop in-process: job -> claim -> fuzz a real target
    -> assimilate crash -> results visible over REST."""
    seed = tmp_path / "seed.bin"
    seed.write_bytes(b"ABC@")  # one bit from the ABCD crash
    _, t = req(server, "/api/target",
               {"name": "corpus_test", "path": corpus_bin("test")})
    _, job = req(server, "/api/job", {
        "target_id": t["id"], "driver": "file",
        "instrumentation": "afl", "mutator": "bit_flip",
        "iterations": 32, "seed_file": str(seed),
        "driver_opts": json.dumps({"path": corpus_bin("test"),
                                   "arguments": "@@"})})
    done = work_loop(f"http://127.0.0.1:{server.port}", "pytest-worker",
                     once=True, in_process=True)
    assert done == 1
    code, results = req(server, f"/api/job/{job['id']}/results")
    assert code == 200
    kinds = {r["result_type"] for r in results}
    assert "crash" in kinds
    # repro file downloads and reproduces: content is the crasher
    crash = next(r for r in results if r["result_type"] == "crash")
    url = f"http://127.0.0.1:{server.port}{crash['repro_file']}"
    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.read() == b"ABCD"
    # the worker re-verified the crash under the debug tier before
    # posting: the result row carries signal-level crash details
    info = json.loads(crash["crash_info"])
    assert info["verified"] is True
    assert info["tier"] == "debug"
    assert info["signal"] == 11          # SIGSEGV (NULL write)
    assert "description" in info
    _, full = req(server, f"/api/job/{job['id']}")
    assert full["status"] == "done"


def test_stats_endpoint_merges_two_workers(server):
    """Acceptance gate: POST two simulated workers' heartbeat
    snapshots, GET the merged fleet view — counters summed, gauges
    max'd, EMA rates weight-averaged (telemetry.aggregate)."""
    def snap(execs, corpus, rate, weight):
        return {"t": 1000.0 + execs, "start_time": 0.0,
                "counters": {"execs": execs, "new_paths": corpus},
                "gauges": {"corpus_size": corpus},
                "rates": {"execs": {"rate": rate, "weight": weight}}}

    code, _ = req(server, "/api/stats/7",
                  {"worker": "w1", "snapshot": snap(1000, 5, 800.0, 1.0)})
    assert code == 201
    code, _ = req(server, "/api/stats/7",
                  {"worker": "w2", "snapshot": snap(500, 9, 200.0, 1.0)})
    assert code == 201
    # latest-wins per worker: w1 heartbeats again with newer totals
    code, _ = req(server, "/api/stats/7",
                  {"worker": "w1", "snapshot": snap(2000, 6, 900.0, 1.0)})
    assert code == 201
    code, view = req(server, "/api/stats/7")
    assert code == 200
    assert view["n_workers"] == 2
    assert set(view["workers"]) == {"w1", "w2"}
    m = view["merged"]
    assert m["counters"]["execs"] == 2500          # summed, latest w1
    assert m["gauges"]["corpus_size"] == 9         # max
    assert abs(m["rates"]["execs"]["rate"] - 550.0) < 1e-6  # wtd mean
    # unknown campaign: empty, not an error
    code, view = req(server, "/api/stats/nope")
    assert code == 200
    assert view["n_workers"] == 0 and view["merged"] is None


def test_worker_job_heartbeats_progress(server, tmp_path):
    """The worker's job runner tails the fuzzer's stats.jsonl and
    POSTs it to /api/stats/<job id> (with a final beat at job end),
    so short in-process jobs still land one progress snapshot."""
    from killerbeez_tpu.manager.worker import run_job
    seed = tmp_path / "seed.bin"
    seed.write_bytes(b"ABC@")
    _, t = req(server, "/api/target", {"name": "tgt-hb"})
    _, job = req(server, "/api/job", {
        "target_id": t["id"], "driver": "file",
        "instrumentation": "jit_harness", "mutator": "bit_flip",
        "iterations": 32, "seed_file": str(seed),
        "instrumentation_opts": json.dumps({"target": "test"})})
    full = req(server, f"/api/job/{job['id']}")[1]
    full["cmdline"] = job["cmdline"]
    status = run_job(f"http://127.0.0.1:{server.port}", full,
                     in_process=True, worker_name="hb-worker")
    assert status == "done"
    code, view = req(server, f"/api/stats/{job['id']}")
    assert code == 200
    assert view["n_workers"] == 1
    assert view["merged"]["counters"]["execs"] == 32


def test_verify_repro_marks_network_findings_unverified():
    """VERDICT weak #5 pinned: a network-delivered crash cannot be
    replayed without the live session — its result row must carry an
    explicit verified=None marker (with the reason), never silently
    omit verification."""
    from killerbeez_tpu.manager.worker import verify_repro
    job = {"instrumentation": "return_code",
           "driver": "network_server",
           "driver_opts": json.dumps({"path": "/bin/true",
                                      "port": 7000})}
    info = verify_repro(job, b"\x01\x02\x03")
    assert info["verified"] is None
    assert "not replayable" in info["reason"]
