import json
import os

import numpy as np
import pytest

from killerbeez_tpu.utils import (
    setup_logging, logging_help, parse_options, add_int_option_to_json,
    read_file, write_buffer_to_file, file_exists, get_temp_filename,
    md5_hex, encode_mem_array, decode_mem_array,
)
from killerbeez_tpu.utils.options import OptionError, format_help
from killerbeez_tpu.utils.serialization import (
    encode_array, decode_array, state_dumps, state_loads,
)
from killerbeez_tpu.utils.logging import FatalError, FATAL_MSG


def test_parse_options_schema():
    schema = {"path": str, "timeout": int, "ratio": float}
    opts = parse_options('{"path": "/bin/x", "timeout": 3}', schema,
                         defaults={"ratio": 2.0})
    assert opts == {"path": "/bin/x", "timeout": 3, "ratio": 2.0}


def test_parse_options_rejects_unknown_and_badtype():
    schema = {"timeout": int}
    with pytest.raises(OptionError):
        parse_options('{"timeoot": 1}', schema)
    with pytest.raises(OptionError):
        parse_options('{"timeout": "x"}', schema)
    with pytest.raises(OptionError):
        parse_options('not json', schema)
    with pytest.raises(OptionError):
        parse_options('{"ratio": true}', {"ratio": float})


def test_parse_options_empty():
    assert parse_options(None, {"a": int}) == {}
    assert parse_options("", None) == {}


def test_add_int_option():
    s = add_int_option_to_json('{"a": 1}', "edges", 1)
    assert json.loads(s) == {"a": 1, "edges": 1}
    s2 = add_int_option_to_json(None, "edges", 1)
    assert json.loads(s2) == {"edges": 1}


def test_format_help():
    h = format_help("file", {"path": str}, {"path": "target binary"})
    assert "path" in h and "file" in h


def test_fileio_roundtrip(tmp_path):
    p = tmp_path / "buf.bin"
    write_buffer_to_file(p, b"ABCD")
    assert file_exists(p)
    assert read_file(p) == b"ABCD"
    assert md5_hex(b"ABCD") == "cb08ca4a7bb5f9683c19133a84872ca7"


def test_temp_filename():
    p = get_temp_filename("kbz_test")
    assert os.path.exists(p)
    os.unlink(p)


def test_mem_array_roundtrip():
    bufs = [b"\x00\x01", b"", b"packet2" * 100]
    assert decode_mem_array(encode_mem_array(bufs)) == bufs


def test_array_codec_roundtrip():
    a = (np.arange(65536) % 251).astype(np.uint8)
    d = encode_array(a)
    assert json.dumps(d)  # json-safe
    np.testing.assert_array_equal(decode_array(d), a)
    d2 = encode_array(a.reshape(256, 256), compress=False)
    np.testing.assert_array_equal(decode_array(d2), a.reshape(256, 256))


def test_state_codec():
    s = state_dumps({"iteration": 5, "x": "y"})
    assert state_loads(s) == {"iteration": 5, "x": "y"}
    assert state_loads("") == {}


def test_logging_config_and_fatal(tmp_path, capsys):
    logf = tmp_path / "log.txt"
    setup_logging(json.dumps({"level": 2, "file": str(logf)}))
    from killerbeez_tpu.utils import INFO_MSG, WARNING_MSG
    INFO_MSG("hidden %d", 1)
    WARNING_MSG("shown %s", "msg")
    with pytest.raises(FatalError):
        FATAL_MSG("boom")
    text = logf.read_text()
    assert "hidden" not in text
    assert "shown msg" in text and "WARNING" in text
    assert "boom" in text and "FATAL" in text
    assert "level" in logging_help()
    # reset for other tests: stream=None resolves sys.stderr at write time
    setup_logging('{"level": 1}')
    from killerbeez_tpu.utils.logging import _state
    _state.stream = None
    _state._fh = None
    _state.filename = None


def test_logging_bad_level():
    with pytest.raises(ValueError):
        setup_logging('{"level": 9}')
