"""Partition-tolerant fleet tier, part 2: sync pull-path failures
under chaos, and THE fleet chaos convergence gates — a simulated
fleet (resilience/fleetsim.py) of in-process gossiping workers
surviving a manager death, a scoped >= 2-round network partition and
a poisoned peer, converging to the fault-free control: identical
union of admitted cov_hashes, zero lost findings, per-worker event
streams stored gapless, the poison quarantined and its peer banned.

The >= 32-worker SIGKILL gate is slow-marked (the fleet-chaos CI
lane runs it); a 6-worker in-process version guards tier-1.
KBZ_FLEET_N scales the gate up (the harness drives ~100 workers)."""

import base64
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from killerbeez_tpu.corpus import CorpusStore, CorpusSync
from killerbeez_tpu.corpus.schedule import make_scheduler
from killerbeez_tpu.corpus.store import CorpusEntry
from killerbeez_tpu.manager.api import ManagerServer
from killerbeez_tpu.resilience import chaos
from killerbeez_tpu.resilience.fleetsim import SimFleet
from killerbeez_tpu.telemetry import Telemetry


@pytest.fixture(autouse=True)
def _no_chaos():
    yield
    chaos.configure(None)


# -- corpus/sync.py pull-path failures under chaos ----------------------


class _Fz:
    """Minimal fuzzer protocol for CorpusSync (telemetry, scheduler,
    store, _seen, feedback) — the sync client can't tell it from the
    loop."""

    def __init__(self, root=None):
        self.telemetry = Telemetry(None)
        self.scheduler = make_scheduler("rr")
        self.scheduler.base_seed = b"S"
        self.store = CorpusStore(root) if root else None
        self._seen = {"new_paths": set()}
        self.feedback = 1


@pytest.fixture
def server(tmp_path):
    s = ManagerServer(port=0, db_path=str(tmp_path / "m.db"))
    s.start()
    yield s, f"http://127.0.0.1:{s.port}"
    chaos.configure(None)
    s.stop()


@pytest.mark.parametrize("mode", ["http500", "timeout", "partition"])
def test_pull_path_failure_backs_off_decorrelated(server, tmp_path,
                                                  mode):
    """Satellite gate: chaos-injected pull failures engage the
    decorrelated round backoff, raise sync_consecutive_failures, and
    a recovered endpoint resets both."""
    s, url = server
    sync = CorpusSync(url, "cs1", worker="puller", interval_s=2.0,
                      attempts=1)
    fz = _Fz(str(tmp_path / "p"))
    assert sync.maybe_sync(fz, force=True)      # healthy baseline
    assert sync.consecutive_failures == 0 and sync._backoff == 0.0
    spec = {"point": "manager_rpc", "mode": mode, "every": 1}
    if mode == "partition":
        spec["match"] = f":{s.port}"            # endpoint-scoped
    chaos.configure({"faults": [spec]})
    backoffs = []
    for i in range(1, 4):
        assert sync.maybe_sync(fz, force=True)
        assert sync.consecutive_failures == i
        assert fz.telemetry.registry.gauges[
            "sync_consecutive_failures"] == i
        backoffs.append(sync._backoff)
    # decorrelated jitter: every failed round's extra delay is drawn
    # from U[interval, 3x previous], never below the interval and
    # never above the cap
    assert all(sync.interval_s <= b <= sync.backoff_cap
               for b in backoffs)
    assert backoffs[-1] <= 3.0 * max(backoffs[:-1]) + 1e-9
    chaos.configure(None)
    assert sync.maybe_sync(fz, force=True)
    assert sync.consecutive_failures == 0 and sync._backoff == 0.0


@pytest.mark.parametrize("mode", ["timeout", "partition"])
def test_recovered_endpoint_drains_requeue_without_dup_arms(
        server, tmp_path, mode):
    """Entries admitted during a partition requeue (never drop), the
    recovered manager receives each exactly once, and the puller
    admits each exactly once — no duplicate arm is ever minted."""
    s, url = server
    pusher = CorpusSync(url, "cs2", worker="pusher", interval_s=0.0,
                        attempts=1)
    fz = _Fz(str(tmp_path / "push"))
    chaos.configure({"faults": [
        {"point": "manager_rpc", "mode": mode, "every": 1}]})
    entries = [CorpusEntry(f"E{i}".encode(), sig=[100 + i])
               for i in range(4)]
    for e in entries[:2]:
        pusher.note_entry(e)
    assert pusher.maybe_sync(fz, force=True)    # fails, requeues
    for e in entries[2:]:
        pusher.note_entry(e)
    assert pusher.maybe_sync(fz, force=True)
    assert pusher.pushed_n == 0
    assert len(pusher._pending) == 4            # requeued, not lost
    chaos.configure(None)
    assert pusher.maybe_sync(fz, force=True)    # drains
    assert pusher.pushed_n == 4
    rows = s.db.get_corpus_entries("cs2", 0)
    assert len(rows) == 4
    # the puller side: admits each exactly once across two rounds
    puller = CorpusSync(url, "cs2", worker="puller", interval_s=0.0,
                        attempts=1)
    fz2 = _Fz(str(tmp_path / "pull"))
    puller.maybe_sync(fz2, force=True)
    assert puller.pulled_n == 4
    arms = [a.md5 for a in fz2.scheduler.arms]
    assert len(arms) == len(set(arms)) == 4
    puller.maybe_sync(fz2, force=True)          # idempotent
    assert puller.pulled_n == 4
    assert len(fz2.scheduler.arms) == 4


# -- the convergence harness -------------------------------------------


def _manager_cov_hashes(url, campaign):
    with urllib.request.urlopen(
            f"{url}/api/corpus/{campaign}?since=0", timeout=10) as r:
        body = json.loads(r.read())
    return {e["cov_hash"] for e in body["entries"]}


def _assert_event_streams_gapless(url, campaign, fleet):
    """Every worker's stored event seqs are 0..n-1, no gaps, no
    duplicates — nothing lost to the kill or the partition, nothing
    double-stored by the re-sends."""
    with urllib.request.urlopen(
            f"{url}/api/events/{campaign}?since=0", timeout=10) as r:
        body = json.loads(r.read())
    by_worker = {}
    for row in body["events"]:
        by_worker.setdefault(row["worker"], []).append(
            row["event"]["seq"])
    for w in fleet.workers:
        seqs = sorted(by_worker.get(w.name, []))
        assert seqs == list(range(w._event_seq)), \
            f"{w.name}: stored seqs {seqs} vs minted {w._event_seq}"


def _control_union(tmp_path, n, plan, seed):
    """The fault-free control: same worker names/seeds/discovery
    plan, healthy manager throughout.  Returns its converged union —
    the set every faulted run must reproduce exactly."""
    s = ManagerServer(port=0,
                      db_path=str(tmp_path / "control.db"))
    s.start()
    url = f"http://127.0.0.1:{s.port}"
    fleet = SimFleet(n, "ctl", url, str(tmp_path / "control"),
                     seed=seed)
    try:
        for find_n in plan:
            fleet.round(discoveries=find_n)
        target = fleet.union()
        assert fleet.rounds_until_converged(target, 32) < 32
        assert all(w.cov_hashes() == target for w in fleet.workers)
        _assert_event_streams_gapless(url, "ctl", fleet)
        return target
    finally:
        fleet.close()
        s.stop()


def test_fleet_converges_through_manager_death_small(tmp_path):
    """Tier-1 guard (6 workers, in-process manager): the hub dies
    mid-campaign, discoveries keep spreading peer-to-peer while it
    is down, and after a restart on the same db+journal the fleet
    AND the manager converge to the fault-free control."""
    n, plan, seed = 6, (2, 1, 1), 11
    control = _control_union(tmp_path, n, plan, seed)

    db = str(tmp_path / "mgr.db")
    s = ManagerServer(port=0, db_path=db)
    s.start()
    port = s.port
    url = f"http://127.0.0.1:{port}"
    fleet = SimFleet(n, "cmp", url, str(tmp_path / "fleet"),
                     seed=seed)
    try:
        fleet.round(discoveries=plan[0])    # healthy: register+seed
        fleet.round()                       # directories complete
        s.stop()                            # the hub dies
        chaos.configure({"faults": [
            {"point": "manager_rpc", "mode": "partition",
             "every": 1, "match": f":{port}"}]})
        # hub-dead rounds: NEW discoveries still reach every peer
        # (epidemic pull with fanout 2 — a straggler can need a few
        # extra rounds, all of them hub-dead)
        fleet.round(discoveries=plan[1])
        fleet.round(discoveries=plan[2])
        dead_rounds = fleet.rounds_until_converged(fleet.union(), 8)
        assert dead_rounds < 8, \
            "gossip did not converge while the hub was dead"
        # restart on the same db (+ journal) and heal the partition
        chaos.configure(None)
        s2 = ManagerServer(port=port, db_path=db)
        s2.start()
        try:
            assert fleet.rounds_until_converged(control, 16) < 16
            assert all(w.cov_hashes() == control
                       for w in fleet.workers)
            # anti-entropy: the requeued pushes catch the manager up
            # within a bounded number of healthy rounds — no finding
            # lost to the death window
            for _ in range(8):
                if _manager_cov_hashes(url, "cmp") >= control:
                    break
                fleet.round()
            assert _manager_cov_hashes(url, "cmp") == control
            _assert_event_streams_gapless(url, "cmp", fleet)
        finally:
            s2.stop()
    finally:
        fleet.close()
        chaos.configure(None)


# -- THE acceptance gate: >= 32 workers, SIGKILL, partition, poison -----


def _free_port():
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _spawn_manager(port, db, journal, timeout=30.0):
    proc = subprocess.Popen(
        [sys.executable, "-m", "killerbeez_tpu.manager",
         "--port", str(port), "--db", db, "--journal", journal],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    url = f"http://127.0.0.1:{port}"
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url + "/api/health",
                                        timeout=2) as r:
                if json.loads(r.read()).get("ok"):
                    return proc
        except OSError:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"manager died at boot (rc {proc.returncode})")
            time.sleep(0.1)
    proc.kill()
    raise RuntimeError("manager never became healthy")


@pytest.mark.slow
def test_fleet_chaos_convergence_gate(tmp_path):
    """The ISSUE 11 acceptance gate: a >= 32-worker simulated fleet
    takes a manager SIGKILL mid-campaign plus a >= 2-round scoped
    partition (one worker's sidecar severed) plus a poisoned peer,
    and still converges to the fault-free control — identical
    cov_hash union everywhere, the restarted manager's table covers
    it (journal + anti-entropy), every event stream gapless, the
    poison never admitted and its source banned."""
    n = int(os.environ.get("KBZ_FLEET_N", "32"))
    plan, seed = (2, 1), 23
    control = _control_union(tmp_path, n, plan, seed)

    port = _free_port()
    db = str(tmp_path / "gate.db")
    journal = db + ".journal"
    proc = _spawn_manager(port, db, journal)
    url = f"http://127.0.0.1:{port}"
    fleet = SimFleet(n, "gate", url, str(tmp_path / "gate"),
                     seed=seed)
    evil = fleet.workers[-1]
    try:
        fleet.round(discoveries=plan[0])    # healthy rounds: the
        fleet.round()                       # directory completes
        forged = evil.poison(4)             # the poisoned peer

        # the power cut: SIGKILL, not a clean stop — the journal is
        # what guarantees the ACKed admissions survive
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)

        # >= 2 hub-dead rounds, with one worker's sidecar ALSO
        # partitioned (scoped: only its endpoint is severed)
        cut = fleet.workers[0].sync.sidecar
        chaos.configure({"faults": [
            {"point": "manager_rpc", "mode": "partition",
             "every": 1, "match": f":{port}"},
            {"point": "gossip_serve", "mode": "partition",
             "every": 1, "match": f":{cut.port}"},
        ]})
        fleet.round(discoveries=plan[1])
        fleet.round()

        # heal everything; restart the manager on the same db+journal
        chaos.configure(None)
        proc = _spawn_manager(port, db, journal)

        rounds = fleet.rounds_until_converged(control, 32)
        assert rounds < 32, "fleet never converged to the control"
        assert all(w.cov_hashes() == control for w in fleet.workers)
        # no finding lost: the restarted manager covers the union
        for _ in range(8):
            if _manager_cov_hashes(url, "gate") >= control:
                break
            fleet.round()
        assert _manager_cov_hashes(url, "gate") == control
        _assert_event_streams_gapless(url, "gate", fleet)

        # the poison: never admitted ANYWHERE, quarantined, banned
        assert not (set(forged) & control)
        for w in fleet.workers:
            assert not (set(forged) & w.cov_hashes())
        assert not (set(forged) & _manager_cov_hashes(url, "gate"))
        quarantined = sum(
            w.registry.counters.get("sync_quarantined", 0)
            for w in fleet.workers)
        banned = sum(w.registry.counters.get("peers_banned", 0)
                     for w in fleet.workers)
        assert quarantined >= 4, "no worker quarantined the poison"
        assert banned >= 1, "nobody banned the poisoned peer"
        assert any(w.sync.bans.total_bans
                   and "w%03d" % (n - 1) in w.sync.bans._prev_ban
                   for w in fleet.workers if w is not evil)

        # kb-fleet's scripting surface sees the quarantine state the
        # CI lane asserts on (counters ride worker heartbeats; here
        # we post one snapshot the way the heartbeat thread would)
        victim = next(w for w in fleet.workers
                      if w.registry.counters.get("sync_quarantined"))
        body = json.dumps({
            "worker": victim.name,
            "snapshot": victim.telemetry.snapshot()}).encode()
        req = urllib.request.Request(
            url + "/api/stats/gate", data=body, method="POST",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10)
        with urllib.request.urlopen(url + "/api/fleet/gate",
                                    timeout=10) as r:
            view = json.loads(r.read())
        stats = view["workers"][victim.name]["stats"]
        assert stats["sync_quarantined"] >= 4
        assert stats["peers_banned"] >= 1
    finally:
        fleet.close()
        chaos.configure(None)
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
