"""Bit-for-bit parity tests for the coverage core.

The oracle is an independent scalar-python transcription of the AFL
contract described in SURVEY §2.3 (classify buckets, has_new_bits
return codes, virgin update, simplify_trace, AND-merge).
"""

import numpy as np
import jax.numpy as jnp

from killerbeez_tpu import MAP_SIZE
from killerbeez_tpu.ops import (
    classify_counts, simplify_trace, has_new_bits, has_new_bits_seq,
    has_new_bits_batch, has_new_bits_with_ignore, update_virgin,
    merge_virgin, build_bitmap, count_non_255_bytes, count_bytes,
    hash_bitmaps, murmur3_32, murmur3_32_np, xxh64,
)

M = 256  # small map for oracle loops


def oracle_classify(b):
    if b == 0:
        return 0
    if b == 1:
        return 1
    if b == 2:
        return 2
    if b == 3:
        return 4
    if b < 8:
        return 8
    if b < 16:
        return 16
    if b < 32:
        return 32
    if b < 128:
        return 64
    return 128


def oracle_has_new_bits(virgin, trace):
    ret = 0
    virgin = virgin.copy()
    for i in range(len(virgin)):
        if trace[i] and (trace[i] & virgin[i]):
            if ret < 2:
                ret = 2 if virgin[i] == 0xFF else 1
        virgin[i] &= ~trace[i] & 0xFF
    return ret, virgin


def test_classify_all_256():
    raw = np.arange(256, dtype=np.uint8)
    got = np.asarray(classify_counts(jnp.asarray(raw)))
    want = np.array([oracle_classify(b) for b in range(256)], dtype=np.uint8)
    np.testing.assert_array_equal(got, want)


def test_simplify_trace():
    raw = np.arange(256, dtype=np.uint8)
    got = np.asarray(simplify_trace(jnp.asarray(raw)))
    want = np.where(raw == 0, 1, 128).astype(np.uint8)
    np.testing.assert_array_equal(got, want)


def test_has_new_bits_parity(rng):
    for trial in range(20):
        virgin = rng.integers(0, 256, M).astype(np.uint8)
        virgin[rng.random(M) < 0.5] = 0xFF
        trace = rng.integers(0, 256, M).astype(np.uint8)
        trace[rng.random(M) < 0.7] = 0  # sparse like real traces
        trace = np.array([oracle_classify(b) for b in trace], dtype=np.uint8)
        want_ret, want_v = oracle_has_new_bits(virgin, trace)
        ret, v = has_new_bits(jnp.asarray(virgin), jnp.asarray(trace))
        assert int(ret) == want_ret, trial
        np.testing.assert_array_equal(np.asarray(v), want_v)


def test_has_new_bits_cases():
    virgin = np.full(M, 0xFF, dtype=np.uint8)
    trace = np.zeros(M, dtype=np.uint8)
    ret, v = has_new_bits(jnp.asarray(virgin), jnp.asarray(trace))
    assert int(ret) == 0  # nothing hit
    trace[7] = 1
    ret, v = has_new_bits(jnp.asarray(virgin), jnp.asarray(trace))
    assert int(ret) == 2  # brand new edge
    # same edge, same count class again -> 0
    ret2, v2 = has_new_bits(v, jnp.asarray(trace))
    assert int(ret2) == 0
    # same edge, new count class -> 1
    trace2 = np.zeros(M, dtype=np.uint8)
    trace2[7] = 2
    ret3, _ = has_new_bits(v, jnp.asarray(trace2))
    assert int(ret3) == 1


def test_seq_matches_singles(rng):
    virgin = np.full(M, 0xFF, dtype=np.uint8)
    traces = np.zeros((16, M), dtype=np.uint8)
    for i in range(16):
        idx = rng.integers(0, M, 4)
        traces[i, idx] = np.array(
            [oracle_classify(c) for c in rng.integers(1, 200, 4)],
            dtype=np.uint8)
    rets, final_v = has_new_bits_seq(jnp.asarray(virgin), jnp.asarray(traces))
    v = virgin
    for i in range(16):
        want, v = oracle_has_new_bits(v, traces[i])
        assert int(rets[i]) == want, i
    np.testing.assert_array_equal(np.asarray(final_v), v)


def test_batch_mode_dedups_and_unions(rng):
    virgin = np.full(M, 0xFF, dtype=np.uint8)
    t = np.zeros((4, M), dtype=np.uint8)
    t[0, 3] = 1
    t[1, 3] = 1          # duplicate of lane 0 -> deduped by hash
    t[2, 9] = 1          # distinct new path
    # lane 3 all zero -> not new
    hashes = hash_bitmaps(jnp.asarray(t))
    rets, v = has_new_bits_batch(jnp.asarray(virgin), jnp.asarray(t), hashes)
    assert list(np.asarray(rets)) == [2, 0, 2, 0]
    # virgin updated with union of the new lanes
    assert np.asarray(v)[3] == 0xFF & ~1
    assert np.asarray(v)[9] == 0xFF & ~1
    # second batch with the same traces: nothing new
    rets2, _ = has_new_bits_batch(v, jnp.asarray(t), hashes)
    assert list(np.asarray(rets2)) == [0, 0, 0, 0]


def test_ignore_mask():
    virgin = np.full(M, 0xFF, dtype=np.uint8)
    trace = np.zeros(M, dtype=np.uint8)
    trace[5] = 1
    ignore = np.zeros(M, dtype=np.uint8)
    ignore[5] = 0xFF
    ret, v = has_new_bits_with_ignore(
        jnp.asarray(virgin), jnp.asarray(trace), jnp.asarray(ignore))
    assert int(ret) == 0
    np.testing.assert_array_equal(np.asarray(v), virgin)
    # ignore is byte-granular: ANY nonzero ignore byte excludes the
    # whole trace byte (reference if (!ignore_bytes[i]) semantics)
    ignore2 = np.zeros(M, dtype=np.uint8)
    ignore2[5] = 0x01
    trace2 = np.zeros(M, dtype=np.uint8)
    trace2[5] = 0x08
    ret2, v2 = has_new_bits_with_ignore(
        jnp.asarray(virgin), jnp.asarray(trace2), jnp.asarray(ignore2))
    assert int(ret2) == 0
    np.testing.assert_array_equal(np.asarray(v2), virgin)


def test_merge_virgin_is_union_of_coverage():
    a = np.full(M, 0xFF, dtype=np.uint8)
    b = np.full(M, 0xFF, dtype=np.uint8)
    a[1] &= ~1 & 0xFF
    b[2] &= ~4 & 0xFF
    m = np.asarray(merge_virgin(jnp.asarray(a), jnp.asarray(b)))
    assert m[1] == 0xFE and m[2] == 0xFB


def test_build_bitmap_counts_and_wrap():
    ids = np.array([[5, 5, 5, 9, 0]], dtype=np.int32)
    valid = np.array([[True, True, True, True, False]])
    bm = np.asarray(build_bitmap(jnp.asarray(ids), jnp.asarray(valid),
                                 map_size=64))
    assert bm.shape == (1, 64)
    assert bm[0, 5] == 3 and bm[0, 9] == 1 and bm[0, 0] == 0
    # uint8 wraparound like the C trampoline's u8 increment
    ids300 = np.zeros((1, 300), dtype=np.int32)
    valid300 = np.ones((1, 300), dtype=bool)
    bm2 = np.asarray(build_bitmap(jnp.asarray(ids300), jnp.asarray(valid300),
                                  map_size=64))
    assert bm2[0, 0] == 300 % 256
    # out-of-range ids (incl. negative, which .at[] would wrap) are dropped
    ids_bad = np.array([[70000, -1, 3]], dtype=np.int32)
    ok = np.ones((1, 3), dtype=bool)
    bm3 = np.asarray(build_bitmap(jnp.asarray(ids_bad), jnp.asarray(ok),
                                  map_size=64))
    assert bm3.sum() == 1 and bm3[0, 3] == 1


def test_counters():
    v = np.full(M, 0xFF, dtype=np.uint8)
    v[3] = 0xFE
    assert int(count_non_255_bytes(jnp.asarray(v))) == 1
    t = np.zeros(M, dtype=np.uint8)
    t[1] = t[8] = 7
    assert int(count_bytes(jnp.asarray(t))) == 2


def test_murmur_device_vs_host(rng):
    for n_words in (1, 4, 16384):
        data = rng.integers(0, 256, n_words * 4).astype(np.uint8).tobytes()
        words = np.frombuffer(data, dtype="<u4")
        got = int(murmur3_32(jnp.asarray(words)))
        want = murmur3_32_np(data)
        assert got == want, n_words


def test_murmur_known_vectors():
    # public MurmurHash3_x86_32 test vectors
    assert murmur3_32_np(b"", seed=0) == 0
    assert murmur3_32_np(b"", seed=1) == 0x514E28B7
    assert murmur3_32_np(b"abc", seed=0) == 0xB3DD93FA
    assert murmur3_32_np(b"Hello, world!", seed=1234) == 0xFAF6CDB3


def test_hash_bitmaps_batched(rng):
    maps = rng.integers(0, 3, (8, 1024)).astype(np.uint8)
    hs = np.asarray(hash_bitmaps(jnp.asarray(maps)))
    assert hs.shape == (8,)
    for i in range(8):
        assert int(hs[i]) == murmur3_32_np(maps[i].tobytes())
    # distinct maps should (overwhelmingly) hash distinctly
    assert len(set(hs.tolist())) == 8


def test_xxh64_known_vectors():
    # public XXH64 test vectors
    assert xxh64(b"") == 0xEF46DB3751D8E999
    assert xxh64(b"a") == 0xD24EC4F1A98C6E5B
    assert xxh64(b"abc") == 0x44BC2CF5AD770999
    long = bytes(range(256)) * 8
    assert xxh64(long) == xxh64(long)
    assert xxh64(long) != xxh64(long[:-1])
    assert xxh64(b"abc", seed=1) != xxh64(b"abc")
