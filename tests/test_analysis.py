"""Static analysis subsystem tests: CFG reconstruction, abstract
interpretation (constants + input-byte taint), the kb-lint defect
checks against synthetic programs containing each defect class, the
auto-dictionary extraction, and the rare-edge static prior's
cold-start/parity contract."""

import json

import numpy as np
import pytest

from killerbeez_tpu.analysis import (
    analyze_dataflow, build_cfg, extract_dictionary, lint_program,
    static_edge_prior,
)
from killerbeez_tpu.analysis.cfg import ENTRY
from killerbeez_tpu.analysis.lint import universe_stats
from killerbeez_tpu.corpus.schedule import Arm, RareEdgeScheduler
from killerbeez_tpu.models import targets, targets_cgc  # noqa: F401
from killerbeez_tpu.models.compiler import Assembler
from killerbeez_tpu.models.vm import OP_BLOCK, OP_HALT, Program
from killerbeez_tpu.tools.lint_tool import main as lint_main


def codes(findings, severity=None):
    return [f.code for f in findings
            if severity is None or f.severity == severity]


# -- CFG reconstruction ----------------------------------------------

def test_cfg_matches_static_edge_universe():
    """The CFG's block-level edges must equal vm.compute_edges' pairs
    on every built-in target (same walk, independent code path)."""
    for name in targets.target_names():
        p = targets.get_target(name)
        cfg = build_cfg(p)
        pairs = set(zip(np.asarray(p.edge_from).tolist(),
                        np.asarray(p.edge_to).tolist()))
        assert set(cfg.edges) == pairs, name


def test_cfg_loop_headers_and_dominators():
    p = targets.get_target("cgc_like")
    cfg = build_cfg(p)
    # the checksum loop head is a loop header, dominated by entry path
    assert cfg.loop_headers, "cgc_like has a loop"
    for h in cfg.loop_headers:
        assert h in cfg.reachable
        assert ENTRY in cfg.dominators[h]
    # every built-in target's hang budget covers its loop-free paths
    for name in targets.target_names():
        prog = targets.get_target(name)
        c = build_cfg(prog)
        assert c.longest_acyclic_path <= prog.max_steps, name


def test_cfg_spin_block_has_no_terminal():
    """hang's spin block: an instruction-level self-loop with no exit
    — no outgoing edges, no terminating block-free path."""
    p = targets.get_target("hang")
    cfg = build_cfg(p)
    spin = 1                            # block 1 is the spin block
    assert cfg.succ[spin] == set()
    assert cfg.term_cost[spin] is None


def test_cfg_longest_path_straight_line():
    a = Assembler("line", max_steps=64)
    a.block()
    for _ in range(10):
        a.addi(1, 1, 1)
    a.halt(0)
    cfg = build_cfg(a.build())
    # block + 10 addi + halt = 12 steps
    assert cfg.longest_acyclic_path == 12


def _irreducible_program(max_steps=64):
    """Blocks B and C branch into each other with neither dominating
    (entry reaches both): C->B is a RETREATING edge a loop-free
    execution can still take, so the longest path must consider
    entry->C->B->end (cheap hop into C, then B's expensive exit)."""
    a = Assembler("irr", max_steps=max_steps)
    a.block()                           # 0: entry
    a.ldi(1, 0)
    a.ldb(2, 1)
    a.ldi(3, 1)
    a.br("eq", 2, 3, "C")
    a.label("B")
    a.block()                           # 1: B
    a.br("eq", 2, 3, "C")               # cheap hop to C
    for _ in range(40):
        a.addi(4, 4, 1)                 # expensive exit path
    a.jmp("end")
    a.label("C")
    a.block()                           # 2: C
    a.br("eq", 2, 0, "B")               # cheap hop back to B
    a.label("end")
    a.block()                           # 3: end
    a.halt(0)
    return a.build()


def test_cfg_irreducible_retreating_edge_longest_path():
    prog = _irreducible_program()
    cfg = build_cfg(prog)
    # neither B nor C dominates the other -> the C->B edge is
    # retreating, not a natural back edge
    assert 1 not in cfg.dominators[2] and 2 not in cfg.dominators[1]
    # entry(5) -> C(2) -> B's long exit(43) -> end(block+halt=2)
    assert cfg.longest_acyclic_path == 52
    assert "max-steps-shortfall" not in codes(lint_program(prog))
    short = _irreducible_program(max_steps=51)
    assert "max-steps-shortfall" in codes(lint_program(short),
                                          "error")


def test_cfg_branch_dense_region_is_polynomial():
    """Reconverging branch diamonds (N branches -> 2^N paths) inside
    one region must not blow up the walk: costs come from a DP over
    the cycle-cut pc graph, not path enumeration.  Also pins that
    build_cfg leaves the process recursion limit alone."""
    import sys
    import time
    from killerbeez_tpu.models.vm import CMP_EQ, OP_BR
    rows = [[OP_BLOCK, 3, 0, 0]]
    for _ in range(48):
        pc = len(rows)
        rows.append([OP_BR, 1, CMP_EQ | (2 << 2), pc + 1])  # diamond
    rows.append([OP_HALT, 0, 0, 0])
    prog = Program(instrs=np.array(rows, dtype=np.int32),
                   name="diamonds", max_steps=64)
    limit = sys.getrecursionlimit()
    t0 = time.time()
    cfg = build_cfg(prog)
    assert time.time() - t0 < 5.0
    assert sys.getrecursionlimit() == limit
    assert cfg.longest_acyclic_path == 50  # block + 48 br + halt


# -- lint: each defect class on a synthetic program ------------------

def test_lint_unreachable_block():
    a = Assembler("unreach", max_steps=64)
    a.block()
    a.jmp("end")
    a.block()                           # tail block jumped over
    a.label("end")
    a.block()
    a.halt(0)
    findings = lint_program(a.build())
    assert "unreachable-block" in codes(findings, "error")
    assert lint_program(targets.get_target("test"),
                        )[0].severity != "error"


def test_lint_max_steps_shortfall():
    a = Assembler("short", max_steps=4)
    a.block()
    for _ in range(10):
        a.addi(1, 1, 1)
    a.halt(0)
    findings = lint_program(a.build())
    f = [x for x in findings if x.code == "max-steps-shortfall"]
    assert f and f[0].severity == "error"
    assert f[0].data["longest_acyclic_path"] == 12


def test_lint_slot_collision():
    # ids chosen so the entry edge (slot id0=8) aliases the edge
    # (b0 -> b1): id1 ^ (id0 >> 1) = 12 ^ 4 = 8
    instrs = np.array([[OP_BLOCK, 8, 0, 0], [OP_BLOCK, 12, 0, 0],
                       [OP_HALT, 0, 0, 0]], dtype=np.int32)
    findings = lint_program(Program(instrs=instrs, name="coll"))
    f = [x for x in findings if x.code == "slot-collision"]
    assert f and f[0].severity == "warning"
    assert sorted(f[0].data["edges"]) == [(-1, 0), (0, 1)]


def test_lint_duplicate_block_id_and_warning(capsys):
    instrs = np.array([[OP_BLOCK, 5, 0, 0], [OP_BLOCK, 5, 0, 0],
                       [OP_HALT, 0, 0, 0]], dtype=np.int32)
    prog = Program(instrs=instrs, name="dup")
    err = capsys.readouterr().err
    assert "duplicate coverage id" in err      # one-line build warning
    f = [x for x in lint_program(prog)
         if x.code == "duplicate-block-id"]
    assert f and f[0].severity == "warning"
    assert f[0].data["blocks"] == [0, 1]       # the exact aliased pair


def test_lint_empty_module():
    instrs = np.array([[OP_BLOCK, 7, 0, 0], [OP_HALT, 0, 0, 0]],
                      dtype=np.int32)
    prog = Program(instrs=instrs, name="em",
                   modules=(("target", 0, 1), ("lib", 1, 1)))
    assert "empty-module" in codes(lint_program(prog), "error")


def test_lint_must_crash_block():
    a = Assembler("mc", max_steps=32)
    a.block()
    a.ldi(1, 0)
    a.ldb(2, 1)
    a.ldi(3, 65)
    a.br("ne", 2, 3, "out")
    a.block()                           # input[0] == 'A': wild store
    a.ldi(4, -1)
    a.stm(4, 2)
    a.halt(0)
    a.label("out")
    a.block()
    a.halt(0)
    findings = lint_program(a.build())
    f = [x for x in findings if x.code == "must-crash-block"]
    assert f and f[0].severity == "info" and f[0].data["block"] == 1


def test_lint_dead_block_constant_fold():
    a = Assembler("dead", max_steps=32)
    a.block()
    a.ldi(1, 3)
    a.ldi(2, 5)
    a.br("lt", 1, 2, "out")             # 3 < 5: always taken
    a.block()                           # CFG-reachable, never runs
    a.label("out")
    a.block()
    a.halt(0)
    findings = lint_program(a.build())
    f = [x for x in findings if x.code == "dead-block"]
    assert f and f[0].severity == "warning" and f[0].data["block"] == 1


def test_lint_register_field_range_and_clip_semantics():
    """Out-of-range register fields are flagged, and the abstract
    interpreter models the engine's clip (LDI a=9 writes r7, not
    r9 & 7 = r1)."""
    from killerbeez_tpu.models.vm import (
        CMP_EQ, OP_BR, OP_CRASH, OP_LDI,
    )
    instrs = np.array([
        [OP_BLOCK, 3, 0, 0],
        [OP_LDI, 9, 7, 0],              # clips to r7 = 7
        [OP_BR, 7, CMP_EQ | (0 << 2), 5],   # r7 == r0? never
        [OP_BLOCK, 4, 0, 0],            # fallthrough: always runs
        [OP_CRASH, 0, 0, 0],
        [OP_BLOCK, 5, 0, 0],            # branch target: never runs
        [OP_HALT, 0, 0, 0],
    ], dtype=np.int32)
    prog = Program(instrs=instrs, name="clip", max_steps=16)
    findings = lint_program(prog)
    f = [x for x in findings if x.code == "register-field-range"]
    assert f and f[0].data == {"pc": 1, "fields": [9]}
    df = analyze_dataflow(prog)
    # with clip semantics r7 == 7, so the eq-branch to block 2 folds
    # false: block 2 is dead, and every live path crashes (blocks 0
    # and 1 are both must-crash)
    assert df.dead_blocks == {2}
    assert df.must_crash_blocks == {0, 1}
    from killerbeez_tpu.models.vm import run_batch
    import jax.numpy as jnp
    res = run_batch(prog, jnp.zeros((1, 8), jnp.uint8),
                    jnp.asarray([1], jnp.int32))
    assert int(res.status[0]) == 2      # FUZZ_CRASH — engine agrees


def test_lint_builtin_targets_clean():
    """Acceptance bar: no error-severity findings on any built-in."""
    for name in targets.target_names():
        findings = lint_program(targets.get_target(name))
        assert not codes(findings, "error"), (name, findings)


# -- compiler satellite: trailing empty module -----------------------

def test_trailing_empty_module_rejected_at_build():
    a = Assembler("tem")
    a.block()
    a.halt(0)
    a.module("tail")                    # no blocks follow
    with pytest.raises(ValueError, match="empty module"):
        a.build()


# -- dataflow / dictionary extraction --------------------------------

def test_dataflow_branch_constants_test_target():
    p = targets.get_target("test")
    df = analyze_dataflow(p)
    consts = {f.const for f in df.branches
              if f.const is not None and f.deps}
    assert {ord("A"), ord("B"), ord("C"), ord("D")} <= consts
    # expect_byte chains pin single byte positions
    deps = {next(iter(f.deps)): f.const for f in df.branches
            if f.deps and len(f.deps) == 1 and f.const is not None}
    assert deps[0] == ord("A") and deps[3] == ord("D")


def test_extract_dictionary_merges_magic_runs():
    toks = extract_dictionary(targets.get_target("test"))
    assert b"ABCD" in toks              # merged positional run
    toks = extract_dictionary(targets.get_target("tlvstack_vm"))
    assert b"STK1" in toks
    assert bytes([0x0d]) in toks        # opcode byte (PRIV)


def test_extract_dictionary_deterministic_ordering():
    """Regression: token order is (first-use pc, bytes) — stable
    across runs AND across any reordering of the branch list (it used
    to follow collection order)."""
    from killerbeez_tpu.analysis.dataflow import (
        BranchFact, DataflowResult,
    )
    prog = targets.get_target("tlvstack_vm")
    base = analyze_dataflow(prog)
    toks = extract_dictionary(prog, base)
    # same facts, reversed and interleaved: identical tokens
    for order in (list(reversed(base.branches)),
                  base.branches[1::2] + base.branches[0::2]):
        shuffled = DataflowResult(branches=order,
                                  reached_pcs=base.reached_pcs)
        assert extract_dictionary(prog, shuffled) == toks
    # the contract itself: a synthetic two-branch program emits the
    # earlier-pc token first even when collected later
    early = BranchFact(pc=2, block=0, cmp="eq", const=0x41,
                       deps=frozenset([5]), always=None)
    late = BranchFact(pc=9, block=1, cmp="eq", const=0x7788,
                      deps=frozenset([0, 1]), always=None)
    df = DataflowResult(branches=[late, early], reached_pcs=set())
    assert extract_dictionary(prog, df) == [
        b"A", (0x7788).to_bytes(2, "big"),
        (0x7788).to_bytes(2, "little")]


def test_extract_dictionary_run_merge_keeps_first_pc_order():
    toks = extract_dictionary(targets.get_target("test"))
    # first-use pc 8 carries both the single and the merged run
    # (bytes break the tie), then the later singles in pc order
    assert toks == [b"A", b"ABCD", b"B", b"C", b"D"]


# -- dataflow over every CGC-grade target ----------------------------

@pytest.mark.parametrize("name", sorted(targets_cgc.VM_SEEDS))
def test_dataflow_cgc_targets_terminate_with_facts(name):
    """Fixpoint terminates on the 100+-block targets and yields
    non-empty branch facts, input-tainted guarded compares included
    (the dictionary/solver signal)."""
    prog = targets.get_target(name)
    df = analyze_dataflow(prog)
    assert df.branches, name
    guarded = [f for f in df.branches
               if f.const is not None and f.deps]
    if name != "magicsum_vm":
        # magic-byte chains at least — except magicsum_vm, the
        # input-to-state micro-family, whose ONLY interesting compare
        # is input-derived vs input-derived (stored field vs computed
        # checksum) BY DESIGN: no byte-vs-constant guard exists for
        # the dictionary/solver signal to read, which is exactly why
        # that family needs operand matching instead
        assert guarded, name
    assert df.reached_pcs               # fixpoint visited the program


@pytest.mark.parametrize("name", sorted(targets_cgc.VM_SEEDS))
def test_dataflow_cgc_no_false_statics_vs_concrete_run(name):
    """No must-crash or dead-block false positives: concrete runs of
    the seed AND the crash reproducer never execute a statically-dead
    block, and whenever they enter a must-crash block the run really
    does crash."""
    from killerbeez_tpu import FUZZ_CRASH
    from killerbeez_tpu.analysis.solver import concrete_run
    prog = targets.get_target(name)
    df = analyze_dataflow(prog)
    seed_fn, crash_fn = targets_cgc.VM_SEEDS[name]
    for data in (seed_fn(), crash_fn()):
        tr = concrete_run(prog, data)
        visited = set(tr.blocks)
        assert not (visited & df.dead_blocks), (name, data)
        if visited & df.must_crash_blocks:
            assert tr.status == FUZZ_CRASH, (name, data)


def test_dictionary_mutator_auto_tokens():
    """Acceptance: the dictionary mutator consumes the auto-extracted
    dictionary of a CGC-class target without any token file."""
    from killerbeez_tpu.mutators.factory import mutator_factory
    m = mutator_factory("dictionary",
                        json.dumps({"target": "tlvstack_vm"}),
                        b"STK1\x01\x05")
    assert len(m.token_lens) > 0
    assert m.get_total_iteration_count() > 0
    bufs, lens = m._generate(np.arange(4, dtype=np.int32))
    assert np.asarray(bufs).shape[0] == 4
    with pytest.raises(ValueError, match="needs tokens"):
        mutator_factory("dictionary", None, b"seed")


def test_cli_dictionary_option_injection():
    from killerbeez_tpu.fuzzer.cli import _augment_dictionary_options
    out = _augment_dictionary_options(
        None, '{"target": "tlvstack_vm"}')
    assert json.loads(out) == {"target": "tlvstack_vm"}
    # explicit token sources are never overridden
    assert _augment_dictionary_options(
        '{"tokens": ["x"]}', '{"target": "t"}') == '{"tokens": ["x"]}'
    assert _augment_dictionary_options(None, None) is None


# -- static edge prior / rare-edge scheduling ------------------------

def test_static_prior_depth_ordering():
    """Edges deep behind branch cascades carry less static mass than
    the entry edge."""
    p = targets.get_target("tlvstack_vm")
    prior = static_edge_prior(p)
    entry_slot = int(np.asarray(p.edge_slot)[
        np.flatnonzero(np.asarray(p.edge_from) == -1)[0]])
    assert prior[entry_slot] == 1.0     # entry edge: all mass
    assert min(prior.values()) < 0.01   # leaves: tiny mass
    assert set(prior) == {int(s) for s in np.asarray(p.edge_slot)}


def _prior_fixture():
    p = targets.get_target("tlvstack_vm")
    prior = static_edge_prior(p)
    slots = sorted(prior, key=prior.get)
    return prior, slots[:2], slots[-2:]  # (prior, rare, common)


def test_rare_edge_static_prior_breaks_cold_start_ties():
    prior, rare, common = _prior_fixture()
    unprimed, primed = RareEdgeScheduler(), \
        RareEdgeScheduler(static_prior=prior)
    for s in (unprimed, primed):
        s.admit(Arm(b"rare-sig", sig=rare))
        s.admit(Arm(b"common-sig", sig=common))
    # cold start: equal dynamic rarity (1) and selections (0) — the
    # unprimed scheduler falls back to newest, the primed one probes
    # the arm holding the statically-rarest edge
    assert unprimed.select()[0] == 1
    assert primed.select()[0] == 0


def test_rare_edge_static_prior_parity_when_dynamics_dominate():
    """Acceptance: once dynamic edge-hit counts differ, selection is
    bit-identical with and without the prior."""
    prior, rare, common = _prior_fixture()
    unprimed, primed = RareEdgeScheduler(), \
        RareEdgeScheduler(static_prior=prior)
    for s in (unprimed, primed):
        # arm 0 carries edges shared by later entries (dynamically
        # common but statically rare); arm 1 stays dynamically rare
        s.admit(Arm(b"a", sig=rare))
        s.admit(Arm(b"b", sig=common))
        s.admit(Arm(b"c", sig=rare))
        s.admit(Arm(b"d", sig=rare))
    picks_u, picks_p = [], []
    for _ in range(8):
        for picks, s in ((picks_u, unprimed), (picks_p, primed)):
            i, _ = s.select()
            picks.append(i)
            s.arms[i][1] += 1           # selection counts diverge
    assert picks_u == picks_p


# -- kb-lint CLI -----------------------------------------------------

def test_kb_lint_builtins_exit_zero(capsys):
    assert lint_main(["--all"]) == 0
    out = capsys.readouterr().out
    assert "tlvstack_vm" in out and "0 error(s)" in out


def test_kb_lint_json_and_error_exit(tmp_path, capsys):
    a = Assembler("bad", max_steps=2)
    a.block()
    a.jmp("end")
    a.block()                           # unreachable
    a.label("end")
    a.block()
    for _ in range(8):
        a.addi(1, 1, 1)                 # max_steps shortfall
    a.halt(0)
    prog = a.build()
    path = tmp_path / "bad.npz"
    np.savez(path, instrs=prog.instrs, name=prog.name,
             mem_size=prog.mem_size, max_steps=prog.max_steps,
             n_blocks=prog.n_blocks, block_ids=np.array(prog.block_ids))
    assert lint_main(["--program-file", str(path), "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["errors"] >= 2
    found = {f["code"] for t in rep["targets"].values()
             for f in t["findings"]}
    assert {"unreachable-block", "max-steps-shortfall"} <= found


def test_kb_lint_duplicate_names_not_conflated(tmp_path, capsys):
    prog = targets.get_target("test")
    paths = []
    for i in (1, 2):
        p = tmp_path / f"p{i}.npz"
        np.savez(p, instrs=prog.instrs, name=prog.name,
                 mem_size=prog.mem_size, max_steps=prog.max_steps)
        paths.append(str(p))
    assert lint_main(["--program-file", paths[0],
                      "--program-file", paths[1], "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert sorted(rep["targets"]) == ["test", "test#2"]


def test_kb_lint_dictionary_flag(capsys):
    assert lint_main(["tlvstack_vm", "--json", "--dict"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert "STK1" in rep["targets"]["tlvstack_vm"]["dictionary"]


def test_universe_stats_shape():
    s = universe_stats(targets.get_target("libtest"))
    assert s["n_modules"] == 2
    assert s["n_blocks"] == 7 and s["n_edges"] == 8
    assert json.dumps(s)                # JSON-serializable


# -- kb-lint --sarif --------------------------------------------------

def test_kb_lint_sarif_clean_targets(capsys):
    assert lint_main(["--all", "--sarif"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "kb-lint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    # warning/info findings exist on the builtins (slot collisions,
    # must-crash planted bugs) but nothing error-level
    assert all(r["level"] != "error" for r in run["results"])
    assert {r["ruleId"] for r in run["results"]} <= rule_ids
    # built-in findings anchor on the target builder's source file
    for r in run["results"]:
        uri = r["locations"][0]["physicalLocation"][
            "artifactLocation"]["uri"]
        assert uri.endswith(("targets.py", "targets_cgc.py",
                             "targets_stateful.py")), uri


def test_kb_lint_sarif_error_levels_and_exit(tmp_path, capsys):
    a = Assembler("bad", max_steps=64)
    a.block()
    a.jmp("end")
    a.block()                           # unreachable -> error
    a.label("end")
    a.block()
    a.halt(0)
    prog = a.build()
    path = tmp_path / "bad.npz"
    np.savez(path, instrs=prog.instrs, name=prog.name,
             mem_size=prog.mem_size, max_steps=prog.max_steps)
    assert lint_main(["--program-file", str(path), "--sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    results = doc["runs"][0]["results"]
    errs = [r for r in results if r["level"] == "error"]
    assert errs and errs[0]["ruleId"] == "unreachable-block"
    loc = errs[0]["locations"][0]["logicalLocations"][0]
    assert loc["fullyQualifiedName"].startswith("bad:pc")
    # GitHub's SARIF ingestion renders results only through a
    # physical location — program-file findings anchor on the .npz
    phys = errs[0]["locations"][0]["physicalLocation"]
    assert phys["artifactLocation"]["uri"].endswith("bad.npz")
    assert phys["region"]["startLine"] == 1
    # one rule per check id, each with a defaultConfiguration level
    rules = doc["runs"][0]["tool"]["driver"]["rules"]
    assert len({r["id"] for r in rules}) == len(rules)
    assert all("level" in r["defaultConfiguration"] for r in rules)


def test_kb_lint_sarif_json_mutually_exclusive(capsys):
    with pytest.raises(SystemExit):
        lint_main(["--json", "--sarif"])


# -- tool wiring -----------------------------------------------------

def test_showmap_static_summary():
    from killerbeez_tpu.tools.showmap import static_summary
    p = targets.get_target("test")
    slots = [int(s) for s in np.asarray(p.edge_slot)[:3]]
    line = static_summary(p, slots)
    assert "7 blocks" in line and "3/11 static slots" in line


def test_extract_dictionary_zoo_cksum_wide_magic():
    """The zoo's cksum gate compares a 32-bit LE word built from the
    first four input bytes — the dictionary must surface the magic in
    both byte orders (the LE rendering is what actually lands in the
    file)."""
    toks = extract_dictionary(targets.get_target(
        "zoo:cksum:style=sum,bug=1"))
    assert b"CKSM" in toks              # little-endian: file order
    assert b"MSKC" in toks              # big-endian companion


def test_dataflow_len_dep_flags_length_comparisons():
    """``BranchFact.len_dep`` marks branches whose operand folds the
    input length — the signal the grammar auto-deriver reads to place
    length fields.  Byte-content gates must stay unflagged."""
    df = analyze_dataflow(targets.get_target("zoo:chain:width=2,bug=0"))
    len_facts = [f for f in df.branches if f.len_dep]
    assert len_facts                    # load_len guard + verdict fold
    assert any(not f.deps for f in len_facts)   # pure length bound
    content = [f for f in df.branches if f.deps and not f.len_dep]
    assert content                      # the 32-bit magic gate


# -- _fold_cmp vs the concrete engine at int32 boundaries ------------
#
# _fold_cmp is the shared fold both constant propagation and the
# value-set tier compare through: a signedness or wrap slip here
# poisons every derived fact.  Pin it against the CONCRETE engine:
# build each operand in-register (LDI + SHL + OR byte chunks — the
# <2^24 field bound forbids wide immediates), branch on it, and
# compare the folded verdict with the block the VM actually walked.

_INT32_EDGE_VALUES = (
    -(1 << 31),                         # INT32_MIN
    -(1 << 31) + 1,
    -1, 0, 1,
    (1 << 31) - 1,                      # INT32_MAX
    (1 << 31),                          # wraps to INT32_MIN
    (1 << 32) - 1,                      # wraps to -1
    0x7FFFFF01,                         # MAX-ish vs small positive
)


def _emit_const32(a, rd, value, scratch):
    """rd = int32(value), built from 8-bit chunks via SHL/OR so every
    instruction field stays below 2^24.  The final OR of the top
    chunk wraps through _i32 exactly like any runtime ALU result."""
    v = value & 0xFFFFFFFF
    a.ldi(rd, (v >> 24) & 0xFF)
    for shift in (16, 8, 0):
        a.ldi(scratch, 8)
        a.alu("shl", rd, rd, scratch)
        a.ldi(scratch, (v >> shift) & 0xFF)
        a.alu("or", rd, rd, scratch)


@pytest.mark.parametrize("cmp_name,sel", [("eq", 0), ("ne", 1),
                                          ("lt", 2), ("ge", 3)])
def test_fold_cmp_matches_concrete_engine_at_int32_boundaries(
        cmp_name, sel):
    from killerbeez_tpu.analysis.dataflow import _fold_cmp, _i32
    from killerbeez_tpu.analysis.solver import concrete_run
    for xv in _INT32_EDGE_VALUES:
        for yv in _INT32_EDGE_VALUES:
            a = Assembler(f"fold_{cmp_name}", mem_size=16,
                          max_steps=128)
            a.block()
            _emit_const32(a, 0, xv, 6)
            _emit_const32(a, 1, yv, 6)
            a.br(cmp_name, 0, 1, "taken")
            a.block()                   # block 1: fallthrough
            a.halt()
            a.label("taken")
            a.block()                   # block 2: taken side
            a.halt()
            prog = a.build()
            trace = concrete_run(prog, b"")
            concrete_taken = 2 in trace.blocks
            folded = _fold_cmp(sel, _i32(xv), _i32(yv))
            assert folded is not None, (cmp_name, xv, yv)
            assert folded == concrete_taken, (cmp_name, xv, yv)
            # and the dataflow pass folds the same verdict end-to-end
            df = analyze_dataflow(prog)
            fact = [f for f in df.branches][0]
            assert fact.always == concrete_taken, (cmp_name, xv, yv)
