"""Hybrid native⇄TPU campaign bridge (killerbeez_tpu/hybrid/,
docs/HYBRID.md): lossless seed translation, proxy-binding
certification, cross-tier triage verdicts, per-tier fleet
reconciliation, and the real-pair e2e (planted proxy finding
confirmed on the real binary; a deliberately divergent proxy yields
``proxy_only`` + a gap report, never a silent drop).

Pure-python pieces (translation, queue, validator taxonomy via an
injected run_fn, scheduler credit, manager folds) run everywhere;
tests using the ``corpus_bin`` fixture execute the real built
binaries and auto-carry the ``native`` marker.
"""

import base64
import glob
import json
import os
import random
import time

import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG, FUZZ_NONE
from killerbeez_tpu.corpus.quarantine import EntryValidator
from killerbeez_tpu.corpus.schedule import (
    Arm, RareEdgeScheduler, make_scheduler,
)
from killerbeez_tpu.corpus.store import (
    CorpusEntry, CorpusStore, VALIDATION_VERDICTS, coverage_hash,
)
from killerbeez_tpu.hybrid import (
    CertificationError, NativeSpec, ProxyBinding, bind,
    certify_binding, get_binding,
)
from killerbeez_tpu.hybrid.reconcile import (
    NativeHeartbeat, fold_tiers, tier_of, validation_summary,
)
from killerbeez_tpu.hybrid.translate import (
    DELIVERY_MODES, TRAIN_MODES, NativeDelivery, from_delivery,
    to_delivery,
)
from killerbeez_tpu.hybrid.validate import (
    VERDICT_CONFIRMED, VERDICT_FLAKY, VERDICT_PROXY_ONLY,
    HybridBridge, NativeValidator, ValidationItem, ValidationQueue,
)
from killerbeez_tpu.stateful.framing import frame_messages, unframe
from killerbeez_tpu.telemetry import MetricsRegistry
from killerbeez_tpu.utils.fileio import md5_hex

M_MAX = 4


# -- seed translation (round-trip property) -----------------------------


def _soups():
    rng = random.Random(0xbeef)
    yield b""
    yield b"A"
    yield b"\x00" * 7
    yield bytes(range(256))
    for n in (3, 17, 255, 256, 300, 1024):
        yield bytes(rng.randrange(256) for _ in range(n))
    # well-framed trains round-trip too (they are just bytes)
    yield frame_messages([b"Lpw", b"QA", b"X"], M_MAX)


@pytest.mark.parametrize("mode", DELIVERY_MODES)
def test_translate_roundtrip_identity_all_modes(mode):
    """from_delivery(to_delivery(buf)) == buf for ARBITRARY byte
    soup in every delivery mode — translation is lossless even where
    the framed parse is deliberately lossy."""
    for buf in _soups():
        d = to_delivery(buf, mode=mode, m_max=M_MAX)
        assert d.mode == mode
        assert from_delivery(d, m_max=M_MAX) == buf


def test_translate_train_modes_parse_framed_sequences():
    msgs = [b"HELLO", b"", b"WORLD"]
    buf = frame_messages(msgs, M_MAX)
    for mode in TRAIN_MODES:
        d = to_delivery(buf, mode=mode, m_max=M_MAX)
        assert d.messages == unframe(buf, M_MAX)
        # frame_messages payload survives the parse exactly
        assert [m for m in d.messages if m or True] == d.messages
        assert from_delivery(d, m_max=M_MAX) == buf


def test_translate_train_modes_require_m_max():
    for mode in TRAIN_MODES:
        with pytest.raises(ValueError):
            to_delivery(b"whatever", mode=mode, m_max=0)


def test_translate_unknown_mode_rejected():
    with pytest.raises(ValueError):
        to_delivery(b"x", mode="carrier-pigeon")


def test_native_built_delivery_reencodes():
    """A delivery built on the native side (raw=None) re-encodes its
    messages through the canonical framing."""
    msgs = [b"ab", b"c"]
    d = NativeDelivery(mode="stdin_train", raw=None,
                       payload=b"".join(msgs), messages=list(msgs))
    assert unframe(from_delivery(d, m_max=M_MAX), M_MAX) == msgs
    # and an EMPTY raw buffer is still "translated", not re-encoded
    d2 = to_delivery(b"", mode="stdin_train", m_max=M_MAX)
    assert from_delivery(d2, m_max=M_MAX) == b""


# -- corpus sidecar schema (tier + validation) --------------------------


def test_entry_sidecar_tier_validation_roundtrip(tmp_path):
    store = CorpusStore(str(tmp_path))
    val = {"verdict": "confirmed", "tier": "native", "repro": 3,
           "repeats": 3, "attempts": 3, "statuses": [2, 2, 2],
           "t": 1234.5}
    e = CorpusEntry(b"SEED", sig=[1, 2], tier="tpu", validation=val)
    assert store.put(e)
    got = {x.md5: x for x in store.load()}[e.md5]
    assert got.tier == "tpu"
    assert got.validation == val


def test_old_sidecar_loads_unchanged(tmp_path):
    """Backcompat regression pin: a pre-hybrid sidecar (no tier /
    validation keys) loads with both fields None and is accepted by
    the EntryValidator untouched."""
    store = CorpusStore(str(tmp_path))
    e = CorpusEntry(b"OLD", sig=[7])
    assert store.put(e)
    meta = json.loads(open(store.meta_path(e.md5)).read())
    # pin: the hybrid keys exist in NEW sidecars...
    assert "tier" in meta and "validation" in meta
    # ...build an OLD one by deleting them wholesale
    for k in ("tier", "validation"):
        del meta[k]
    with open(store.meta_path(e.md5), "w") as f:
        json.dump(meta, f)
    got = {x.md5: x for x in store.load()}[e.md5]
    assert got.tier is None and got.validation is None
    entry, reason = EntryValidator().validate({
        "content_b64": base64.b64encode(b"OLD").decode(),
        "md5": e.md5, "cov_hash": coverage_hash([7], b"OLD"),
        "meta": meta})
    assert reason is None and entry.tier is None


def test_update_validation_rewrites_sidecar(tmp_path):
    store = CorpusStore(str(tmp_path))
    e = CorpusEntry(b"PARENT", sig=[3])
    store.put(e)
    rec = {"verdict": "confirmed", "repro": 3, "repeats": 3}
    assert store.update_validation(e.md5, rec) is True
    got = {x.md5: x for x in store.load()}[e.md5]
    assert got.validation["verdict"] == "confirmed"
    # no sidecar -> False, never an exception
    assert store.update_validation("f" * 32, rec) is False


def _row(buf, sig=None, **meta_over):
    sig = sorted(sig or [])
    meta = {"sig": sig or None, "md5": md5_hex(buf),
            "cov_hash": coverage_hash(sig or None, buf),
            "seq": 0, "source": "local"}
    meta.update(meta_over)
    return {"worker": "w", "md5": md5_hex(buf),
            "cov_hash": coverage_hash(sig or None, buf),
            "content_b64": base64.b64encode(buf).decode(),
            "meta": meta}


def test_entry_validator_accepts_bounded_hybrid_meta():
    row = _row(b"DATA", [1], tier="native",
               validation={"verdict": "proxy_only", "tier": "native",
                           "repro": 0, "repeats": 3,
                           "statuses": [0, 0, 0], "t": 1.0,
                           "detail": "x"})
    entry, reason = EntryValidator().validate(row)
    assert reason is None
    assert entry.tier == "native"
    assert entry.validation["verdict"] == "proxy_only"


@pytest.mark.parametrize("mutate,expect", [
    (dict(tier=7), "schema:tier"),
    (dict(tier=""), "schema:tier"),
    (dict(tier="x" * 33), "schema:tier"),
    (dict(tier="evil tier!"), "schema:tier"),
    (dict(validation="confirmed"), "schema:validation"),
    (dict(validation={"verdict": "certainly"}), "schema:validation"),
    (dict(validation={"verdict": "flaky", "repro": -1}),
     "schema:validation"),
    (dict(validation={"verdict": "flaky", "repeats": 5000}),
     "schema:validation"),
    (dict(validation={"verdict": "flaky", "statuses": [2] * 65}),
     "schema:validation"),
    (dict(validation={"verdict": "flaky", "statuses": ["boom"]}),
     "schema:validation"),
    (dict(validation={"verdict": "flaky", "detail": "d" * 257}),
     "schema:validation"),
    (dict(validation={"verdict": "flaky", "tier": "t" * 33}),
     "schema:validation"),
])
def test_entry_validator_rejects_malformed_hybrid_meta(mutate, expect):
    entry, reason = EntryValidator().validate(_row(b"DATA", [1],
                                                   **mutate))
    assert entry is None and reason == expect


# -- native spec deliverability -----------------------------------------


def test_native_spec_refuses_undeliverable_modes():
    """A spec ExecTarget cannot actually deliver is refused at
    construction: running the binary without its payload would make
    every genuinely-crashing finding classify as proxy_only."""
    with pytest.raises(ValueError, match="argv"):
        NativeSpec(argv=["/bin/true"], delivery="argv")
    with pytest.raises(ValueError, match="input_file"):
        NativeSpec(argv=["/bin/true"], delivery="file")
    spec = NativeSpec(argv=["/bin/true"], delivery="file",
                      input_file="/tmp/kbz-in.bin")
    assert spec.input_file == "/tmp/kbz-in.bin"


# -- validation queue ---------------------------------------------------


def _item(buf=b"X", kind="crash", t=None):
    return ValidationItem(kind, buf, md5_hex(buf), t=t)


def test_validation_queue_bounds_and_age():
    q = ValidationQueue(cap=2)
    now = time.time()
    assert q.put(_item(b"a", t=now - 50.0))
    assert q.put(_item(b"b", t=now))
    # full: REJECTED and counted, never silently grown
    assert not q.put(_item(b"c"))
    assert q.dropped == 1 and q.depth() == 2
    assert q.oldest_age(now=now) == pytest.approx(50.0)
    got = q.get(0.0)
    assert got.buf == b"a"
    q.get(0.0)
    assert q.get(0.0) is None and q.oldest_age() == 0.0


# -- verdict taxonomy (injected native side) ----------------------------


def _binding():
    return ProxyBinding(name="fake", proxy_target="test",
                        native=NativeSpec(argv=["/bin/true"]))


def _validate(run_fn, kind="crash", repeats=3, **kw):
    sleeps = []
    v = NativeValidator(_binding(), repeats=repeats, run_fn=run_fn,
                        sleep_fn=sleeps.append, **kw)
    rec = v.validate(_item(kind=kind))
    return rec, sleeps


def test_verdict_confirmed():
    rec, _ = _validate(lambda buf: FUZZ_CRASH)
    assert rec["verdict"] == VERDICT_CONFIRMED
    assert rec["repro"] == 3 and rec["statuses"] == [2, 2, 2]


def test_verdict_proxy_only():
    rec, _ = _validate(lambda buf: FUZZ_NONE)
    assert rec["verdict"] == VERDICT_PROXY_ONLY and rec["repro"] == 0


def test_verdict_flaky_partial_repro():
    it = iter([FUZZ_CRASH, FUZZ_NONE, FUZZ_CRASH])
    rec, _ = _validate(lambda buf: next(it))
    assert rec["verdict"] == VERDICT_FLAKY and rec["repro"] == 2


def test_verdict_hang_kind_matches_hangs_not_crashes():
    rec, _ = _validate(lambda buf: FUZZ_HANG, kind="hang")
    assert rec["verdict"] == VERDICT_CONFIRMED
    rec, _ = _validate(lambda buf: FUZZ_CRASH, kind="hang")
    assert rec["verdict"] == VERDICT_PROXY_ONLY


def test_transient_native_errors_retry_with_backoff():
    """-2 statuses retry with exponential backoff inside the repeat
    before counting; a recovered substrate still confirms."""
    seq = iter([FUZZ_ERROR, FUZZ_ERROR, FUZZ_CRASH,   # repeat 1
                FUZZ_CRASH,                            # repeat 2
                FUZZ_CRASH])                           # repeat 3
    rec, sleeps = _validate(lambda buf: next(seq))
    assert rec["verdict"] == VERDICT_CONFIRMED
    assert rec["attempts"] == 5
    assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]


def test_all_errors_is_flaky_not_proxy_gap():
    """A substrate that never executes must NOT produce a proxy-gap
    claim — undecided, flagged as native-exec-error."""
    rec, sleeps = _validate(lambda buf: FUZZ_ERROR, repeats=2)
    assert rec["verdict"] == VERDICT_FLAKY
    assert rec["detail"] == "native-exec-error"
    assert rec["attempts"] == 8 and len(sleeps) == 8


def test_repeats_clamped_to_sidecar_schema_bound():
    """--hybrid-repeats beyond the 64-status sidecar bound is clamped
    so the minted record always syncs past peer EntryValidators."""
    from killerbeez_tpu.corpus.store import MAX_VALIDATION_REPEATS
    v = NativeValidator(_binding(), repeats=1000,
                        run_fn=lambda buf: FUZZ_CRASH)
    assert v.repeats == MAX_VALIDATION_REPEATS
    rec = v.validate(_item())
    assert len(rec["statuses"]) == MAX_VALIDATION_REPEATS
    row = _row(b"DATA", [1],
               validation={"verdict": rec["verdict"],
                           "repro": rec["repro"],
                           "repeats": rec["repeats"],
                           "statuses": rec["statuses"]})
    entry, reason = EntryValidator().validate(row)
    assert reason is None, reason


# -- scheduler credit ---------------------------------------------------


def test_note_validation_credits_finding_and_parent():
    s = make_scheduler("bandit")
    parent = Arm(b"PARENT")
    child = Arm(b"CHILD", parent=parent.md5)
    other = Arm(b"OTHER")
    for a in (parent, child, other):
        s.admit(a)
    s.note_validation(child.md5, "confirmed", parent=parent.md5)
    assert child[2] == pytest.approx(s.CONFIRM_CREDIT)
    assert parent[2] == pytest.approx(s.CONFIRM_CREDIT)
    assert other[2] == 0.0
    assert {child.md5, parent.md5} <= s.confirmed_md5s
    # idempotent per finding md5
    s.note_validation(child.md5, "confirmed", parent=parent.md5)
    assert child[2] == pytest.approx(s.CONFIRM_CREDIT)
    # other verdicts never credit
    s.note_validation(other.md5, "proxy_only")
    s.note_validation(other.md5, "flaky")
    assert other[2] == 0.0 and other.md5 not in s.confirmed_md5s


def test_confirmed_set_rides_checkpoint_state():
    s = make_scheduler("bandit")
    # pre-hybrid checkpoints stay shape-identical: no key when empty
    assert "confirmed" not in s.state_dict()
    s.note_validation("a" * 32, "confirmed", parent="b" * 32)
    d = s.state_dict()
    assert sorted(d["confirmed"]) == sorted(["a" * 32, "b" * 32])
    s2 = make_scheduler("bandit")
    s2.load_state(d)
    assert s2.confirmed_md5s == s.confirmed_md5s


def test_rare_edge_confirmed_outranks_equal_rarity():
    s = RareEdgeScheduler()
    a = Arm(b"AAAA", sig=[1])
    b = Arm(b"BBBB", sig=[2])
    s.admit(a)
    s.admit(b)
    # equal rarity, equal selections: the NEWER arm (b) wins the
    # historical seq tiebreak...
    i, _ = s.select()
    assert s.arms[i] is b
    # ...until a earns native confirmation: halved rarity outranks
    s.note_validation(a.md5, "confirmed")
    i, _ = s.select()
    assert s.arms[i] is a


def test_rare_edge_parity_with_empty_confirmed_set():
    """Non-confirmed verdicts never enter the confirmed set, so a
    campaign whose validations all came back proxy_only/flaky selects
    bit-identically to one with no hybrid bridge (parity pin)."""
    def drive(s, poke):
        for arm in (Arm(b"AAAA", sig=[1]), Arm(b"BBBB", sig=[2]),
                    Arm(b"CCCC", sig=[1, 2])):
            s.admit(arm)
        if poke:
            s.note_validation(md5_hex(b"BBBB"), "proxy_only")
            s.note_validation(md5_hex(b"CCCC"), "flaky")
        picks = []
        for _ in range(6):
            i, _ = s.select()
            s.credit_period(s.arms[i] if i is not None else None)
            picks.append(i)
        return picks
    assert drive(RareEdgeScheduler(), True) \
        == drive(RareEdgeScheduler(), False)


# -- bridge fold (stub campaign) ----------------------------------------


class _StubTelemetry:
    def __init__(self):
        self.registry = MetricsRegistry()
        self.events = []

    def event(self, etype, **fields):
        self.events.append({"type": etype, **fields})


class _StubFuzzer:
    def __init__(self, out, store=None):
        self.telemetry = _StubTelemetry()
        self.output_dir = str(out)
        self.write_findings = True
        self.store = store
        self.scheduler = make_scheduler("bandit")


def _mk_bridge(run_fn, **kw):
    return HybridBridge(
        _binding(), workers=0,
        validator=NativeValidator(_binding(), repeats=3,
                                  run_fn=run_fn), **kw)


def test_bridge_fold_confirmed_and_proxy_gap(tmp_path):
    """The full loop-side contract in one pass: a confirming and a
    diverging finding enqueue -> pump -> fold, and every artifact
    lands (counters, events, finding sidecar, corpus write-back,
    scheduler credit, proxy-gap report)."""
    store = CorpusStore(str(tmp_path / "corpus"))
    fz = _StubFuzzer(tmp_path, store=store)
    crash_buf, gap_buf = b"CRASH", b"NOPE"
    crash_md5, gap_md5 = md5_hex(crash_buf), md5_hex(gap_buf)
    parent = Arm(b"GENERATOR")
    fz.scheduler.admit(parent)
    # the confirming finding is also a corpus entry (write-back path)
    store.put(CorpusEntry(crash_buf, sig=[9]))
    bridge = _mk_bridge(
        lambda buf: FUZZ_CRASH if buf == crash_buf else FUZZ_NONE)
    assert bridge.enqueue("crash", crash_buf, crash_md5,
                          parent=parent.md5)
    assert bridge.enqueue("crash", gap_buf, gap_md5)
    assert not bridge.enqueue("crash", crash_buf, crash_md5), \
        "enqueue must be idempotent per md5"
    assert bridge.pump() == 2
    assert bridge.fold(fz) == 2

    snap = fz.telemetry.registry.snapshot()["counters"]
    assert snap["hybrid_validations"] == 2
    assert snap["hybrid_confirmed"] == 1
    assert snap["hybrid_proxy_only"] == 1
    assert snap["hybrid_proxy_gaps"] == 1

    by_type = {}
    for e in fz.telemetry.events:
        by_type.setdefault(e["type"], []).append(e)
    verdicts = {e["md5"]: e["verdict"]
                for e in by_type["cross_tier_validate"]}
    assert verdicts == {crash_md5: "confirmed",
                        gap_md5: "proxy_only"}
    assert by_type["proxy_gap"][0]["md5"] == gap_md5

    # finding sidecar + corpus write-back + scheduler credit
    sc = json.load(open(tmp_path / "crashes" / f"{crash_md5}.json"))
    assert sc["validation"]["verdict"] == "confirmed"
    got = {x.md5: x for x in store.load()}[crash_md5]
    assert got.validation["verdict"] == "confirmed"
    assert parent[2] == pytest.approx(fz.scheduler.CONFIRM_CREDIT)

    # the machine-readable gap contract
    report = json.load(open(
        tmp_path / "proxy_gaps" / f"{gap_md5}.json"))
    assert report["schema"] == "kbz-proxy-gap-v1"
    assert report["binding"] == "fake"
    assert report["native"]["repro"] == 0
    assert report["native"]["statuses"] == [0, 0, 0]

    # queue gauges always posted
    g = fz.telemetry.registry.snapshot()["gauges"]
    assert g["validation_queue_depth"] == 0

    # the native heartbeat payload carries the verdict breakdown —
    # CLI --sync-manager campaigns have no TPU-side stats reporter,
    # so kb-fleet's verdict split comes from THIS snapshot
    hc = bridge.snapshot()["counters"]
    assert hc["hybrid_validations"] == 2
    assert hc["hybrid_confirmed"] == 1
    assert hc["hybrid_proxy_only"] == 1
    assert hc["hybrid_proxy_gaps"] == 1


def test_enqueue_readmits_after_queue_full_drop(tmp_path):
    """A finding the FULL queue rejected must stay eligible: the
    dedup key is recorded only on admission, so the same md5 can be
    enqueued again once the queue drains."""
    bridge = _mk_bridge(lambda buf: FUZZ_CRASH, queue_cap=1)
    assert bridge.enqueue("crash", b"A", md5_hex(b"A"))
    assert not bridge.enqueue("crash", b"B", md5_hex(b"B"))  # full
    assert bridge.queue.dropped == 1
    assert bridge.pump() == 1
    assert bridge.enqueue("crash", b"B", md5_hex(b"B")), \
        "a dropped finding must not be dedup-blocked forever"
    # admitted findings stay idempotent
    assert not bridge.enqueue("crash", b"A", md5_hex(b"A"))
    assert bridge.enqueued == 2


def test_bridge_finish_drains_without_workers(tmp_path):
    fz = _StubFuzzer(tmp_path)
    bridge = _mk_bridge(lambda buf: FUZZ_CRASH)
    bridge.enqueue("crash", b"A", md5_hex(b"A"))
    bridge.finish(fz)
    c = fz.telemetry.registry.snapshot()["counters"]
    assert c["hybrid_confirmed"] == 1
    assert bridge.queue.depth() == 0


def test_bridge_worker_thread_e2e(tmp_path):
    """workers=1: validation happens off-thread, fold on the caller —
    the single-writer discipline end to end."""
    fz = _StubFuzzer(tmp_path)
    bridge = HybridBridge(
        _binding(), workers=1,
        validator_factory=lambda: NativeValidator(
            _binding(), repeats=2, run_fn=lambda buf: FUZZ_CRASH))
    for i in range(4):
        bridge.enqueue("crash", bytes([i]), md5_hex(bytes([i])))
    bridge.finish(fz, drain_timeout=10.0)
    c = fz.telemetry.registry.snapshot()["counters"]
    assert c["hybrid_validations"] == 4
    assert c["hybrid_confirmed"] == 4
    assert bridge.snapshot()["counters"]["hybrid_validations"] == 4


def test_bridge_multi_worker_validators_are_private(tmp_path):
    """workers=2: each native worker thread owns its own validator
    (and thus its own ExecTarget) — a shared handle would race under
    the retry path's close()/reopen."""
    import threading as _threading

    made = []
    used_by = {}
    lock = _threading.Lock()

    def factory():
        def run(buf, _v=len(made)):
            with lock:
                used_by.setdefault(_v, set()).add(
                    _threading.current_thread().name)
            return FUZZ_CRASH
        v = NativeValidator(_binding(), repeats=2, run_fn=run)
        made.append(v)
        return v

    fz = _StubFuzzer(tmp_path)
    bridge = HybridBridge(_binding(), workers=2,
                          validator_factory=factory)
    # loop-side validator + one per worker, all distinct instances
    assert len(made) == 3
    assert len({id(v) for v in made}) == 3
    assert len(bridge._worker_validators) == 2
    assert bridge.validator not in bridge._worker_validators
    for i in range(8):
        bridge.enqueue("crash", bytes([i]), md5_hex(bytes([i])))
    bridge.finish(fz, drain_timeout=10.0)
    c = fz.telemetry.registry.snapshot()["counters"]
    assert c["hybrid_validations"] == 8
    assert c["hybrid_confirmed"] == 8
    # no validator instance was ever driven from two threads
    assert all(len(threads) == 1 for threads in used_by.values())


def test_bridge_validator_exception_becomes_flaky(tmp_path):
    def boom(buf):
        raise RuntimeError("native side exploded")
    fz = _StubFuzzer(tmp_path)
    bridge = HybridBridge(
        _binding(), workers=1,
        validator_factory=lambda: NativeValidator(
            _binding(), run_fn=boom, sleep_fn=lambda s: None))
    bridge.enqueue("crash", b"A", md5_hex(b"A"))
    bridge.finish(fz, drain_timeout=10.0)
    c = fz.telemetry.registry.snapshot()["counters"]
    assert c["hybrid_flaky"] == 1, \
        "a dying validator must yield a visible verdict, not a drop"


# -- per-tier reconciliation --------------------------------------------


def test_tier_of_defaults_untagged_to_tpu():
    assert tier_of(None) == "tpu"
    assert tier_of({}) == "tpu"
    assert tier_of({"tier": 7}) == "tpu"
    assert tier_of({"tier": "native"}) == "native"


def _hb_snap(execs, **extra_counters):
    return {"t": time.time(), "elapsed": 10.0,
            "counters": {"execs": execs, "new_paths": 0,
                         "crashes": 0, **extra_counters},
            "gauges": {}, "rates": {}, "derived": {}}


def test_fold_tiers_groups_and_merges():
    rows = [{"worker": "w1", "meta": {"tier": "tpu"}},
            {"worker": "w2", "meta": None},
            {"worker": "w3-native", "meta": {"tier": "native"}}]
    stats = {"w1": {"snapshot": _hb_snap(100)},
             "w2": {"snapshot": _hb_snap(50)},
             "w3-native": {"snapshot": _hb_snap(
                 7, hybrid_validations=3)}}
    statuses = {"w1": "healthy", "w2": "stale",
                "w3-native": "healthy"}
    tiers = fold_tiers(rows, stats, statuses)
    assert set(tiers) == {"tpu", "native"}
    assert tiers["tpu"]["n_workers"] == 2
    assert tiers["tpu"]["counters"]["execs"] == 150
    assert tiers["tpu"]["counts"] == {"healthy": 1, "stale": 1}
    assert tiers["native"]["counters"]["hybrid_validations"] == 3


def test_validation_summary_shapes():
    assert validation_summary(None)["validations"] == 0
    s = validation_summary({
        "counters": {"hybrid_validations": 5, "hybrid_confirmed": 3,
                     "hybrid_proxy_only": 1, "hybrid_flaky": 1,
                     "hybrid_proxy_gaps": 1},
        "gauges": {"validation_queue_depth": 2,
                   "validation_queue_age": 8.5}})
    assert s["validations"] == 5
    assert s["verdicts"] == {"confirmed": 3, "proxy_only": 1,
                             "flaky": 1}
    assert s["proxy_gaps"] == 1
    assert s["queue_depth"] == 2 and s["queue_age_s"] == 8.5


def test_validation_backlog_alert_rule():
    from killerbeez_tpu.manager.db import ManagerDB
    from killerbeez_tpu.manager.fleet import FleetConfig, FleetMonitor
    db = ManagerDB()
    mon = FleetMonitor(db, FleetConfig(
        monitor_interval=0.0, series_interval=1e9,
        validation_backlog_after=120.0))
    now = 1000.0

    def beat(age, t):
        db.note_fleet_worker("c", "w1", now=t)
        snap = _hb_snap(100)
        snap["gauges"] = {"validation_queue_depth": 3,
                          "validation_queue_age": age}
        snap["t"] = t
        db.upsert_campaign_stats("c", "w1", snap)

    beat(10.0, now)
    mon.tick(now=now)
    assert not [a for a in mon.alerts("c")
                if a["alert"] == "validation_backlog" and a["active"]]
    beat(180.0, now + 5.0)
    mon.tick(now=now + 5.0)
    active = [a for a in mon.alerts("c")
              if a["alert"] == "validation_backlog" and a["active"]]
    assert active and active[0]["details"]["queue_depth"] == 3
    # queue drains -> falling edge
    snap = _hb_snap(200)
    snap["gauges"] = {"validation_queue_depth": 0,
                      "validation_queue_age": 0.0}
    db.upsert_campaign_stats("c", "w1", snap)
    mon.tick(now=now + 10.0)
    assert not [a for a in mon.alerts("c")
                if a["alert"] == "validation_backlog" and a["active"]]


def test_fleet_view_exposes_tiers_and_validation():
    from killerbeez_tpu.manager.db import ManagerDB
    from killerbeez_tpu.manager.fleet import (
        FleetConfig, fleet_view, render_fleet_metrics,
    )
    db = ManagerDB()
    cfg = FleetConfig()
    now = 1000.0
    db.note_fleet_worker("c", "w1", meta={"tier": "tpu"}, now=now)
    db.note_fleet_worker("c", "w1-native", meta={"tier": "native"},
                         now=now)
    snap = _hb_snap(1000, hybrid_validations=2, hybrid_confirmed=1,
                    hybrid_proxy_only=1)
    snap["gauges"] = {"validation_queue_depth": 1,
                      "validation_queue_age": 3.0}
    db.upsert_campaign_stats("c", "w1", snap)
    db.upsert_campaign_stats("c", "w1-native", _hb_snap(12))
    body = fleet_view(db, cfg, "c", now=now + 1.0)
    assert set(body["tiers"]) == {"tpu", "native"}
    assert body["tiers"]["native"]["n_workers"] == 1
    assert body["validation"]["validations"] == 2
    assert body["validation"]["verdicts"]["confirmed"] == 1
    assert body["validation"]["queue_depth"] == 1
    # per-worker summary carries the hybrid numbers kb-fleet prints
    ws = body["workers"]["w1"]["stats"]
    assert ws["hybrid_validations"] == 2
    assert ws["validation_queue_depth"] == 1
    # /metrics: per-tier + verdict series appear for hybrid fleets
    text = render_fleet_metrics(db, cfg, now=now + 1.0)
    assert 'kbz_fleet_tier_workers{campaign="c",tier="native"}' \
        in text
    assert 'kbz_hybrid_validations_total{campaign="c",' \
           'verdict="confirmed"} 1' in text
    assert "kbz_validation_queue_depth" in text


def test_pure_tpu_fleet_metrics_unchanged():
    """Gating parity: a fleet with no tier tags and no hybrid
    counters exports EXACTLY the historical series set."""
    from killerbeez_tpu.manager.db import ManagerDB
    from killerbeez_tpu.manager.fleet import (
        FleetConfig, fleet_view, render_fleet_metrics,
    )
    db = ManagerDB()
    db.note_fleet_worker("c", "w1", now=1000.0)
    db.upsert_campaign_stats("c", "w1", _hb_snap(100))
    text = render_fleet_metrics(db, FleetConfig(), now=1001.0)
    assert "kbz_fleet_tier_workers" not in text
    assert "kbz_hybrid_validations" not in text
    body = fleet_view(db, FleetConfig(), "c", now=1001.0)
    assert set(body["tiers"]) == {"tpu"}
    assert body["validation"]["validations"] == 0


def test_kb_fleet_json_shows_tiers_and_queue(capsys):
    """Satellite: kb-fleet --json exposes per-tier worker counts and
    the validation-queue depth through a LIVE manager, fed by the
    bridge's own NativeHeartbeat."""
    from killerbeez_tpu.manager.api import ManagerServer
    from killerbeez_tpu.tools.fleet_tool import main as fleet_main
    s = ManagerServer(port=0)
    s.start()
    try:
        url = f"http://127.0.0.1:{s.port}"
        import urllib.request
        req = urllib.request.Request(
            f"{url}/api/stats/c",
            data=json.dumps({"worker": "w1",
                             "snapshot": _hb_snap(100),
                             "meta": {"tier": "tpu"}}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10).read()
        bridge = HybridBridge(_binding(), workers=0)
        bridge.enqueue("crash", b"Q", md5_hex(b"Q"))  # queued, unvalidated
        hb = NativeHeartbeat(bridge, url, "c", "w1")
        assert hb.post_once()
        assert fleet_main([url, "--campaign", "c", "--json"]) == 0
        body = json.loads(capsys.readouterr().out)
        assert set(body["tiers"]) == {"tpu", "native"}
        assert body["tiers"]["native"]["n_workers"] == 1
        assert body["validation"]["queue_depth"] == 1
        # the human rendering shows the tier column / hybrid lines
        assert fleet_main([url, "--campaign", "c"]) == 0
        text = capsys.readouterr().out
        assert "tiers   :" in text and "native" in text
    finally:
        s.stop()


# -- real-pair certification + e2e (native marker via corpus_bin) -------


def test_builtin_bindings_certify_on_real_binaries(corpus_bin):
    for name in ("test", "test_safe"):
        cert = certify_binding(get_binding(name))
        assert cert["certified"] is True, cert
        assert cert["proxy"]["verdict"] == cert["native"]["verdict"]


def test_divergent_benign_seed_refuses_bind(corpus_bin):
    """A binding whose BENIGN seed already disagrees across tiers is
    miswired and must refuse to bind (stand-down rule)."""
    safe = get_binding("test_safe")
    broken = ProxyBinding(name="broken", proxy_target="test",
                          native=safe.native, benign_seed=b"ABCD")
    cert = certify_binding(broken)
    assert cert["certified"] is False
    with pytest.raises(CertificationError):
        bind(broken, certify=True, strict=True)


def _run_campaign(tmp_path, binding_name, seed=b"ABCD", execs=512):
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.hybrid import make_bridge
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory
    instr = instrumentation_factory("jit_harness",
                                    json.dumps({"target": "test"}))
    mut = mutator_factory("havoc", '{"seed": 7}', seed)
    drv = driver_factory("file", None, instr, mut)
    bridge = make_bridge(binding_name, repeats=3, queue_cap=32,
                         workers=0)
    out = tmp_path / "out"
    fz = Fuzzer(drv, output_dir=str(out), batch_size=64,
                write_findings=True, feedback=8, hybrid=bridge)
    fz.run(execs)
    events = [json.loads(line)
              for line in open(out / "events.jsonl")]
    counters = fz.telemetry.registry.snapshot()["counters"]
    return out, bridge, events, counters


def test_hybrid_e2e_planted_finding_confirmed(tmp_path, corpus_bin):
    """The acceptance e2e: a planted proxy crash ("ABCD" on the test
    KBVM target) translates, replays on the REAL binary and comes
    back ``confirmed`` in the finding sidecar and event stream."""
    out, bridge, events, c = _run_campaign(tmp_path, "test")
    md5 = md5_hex(b"ABCD")
    assert c.get("hybrid_confirmed", 0) >= 1
    ctv = {e["md5"]: e for e in events
           if e["type"] == "cross_tier_validate"}
    assert ctv[md5]["verdict"] == "confirmed"
    assert ctv[md5]["repro"] == 3
    sc = json.load(open(out / "crashes" / f"{md5}.json"))
    assert sc["validation"]["verdict"] == "confirmed"
    assert sc["validation"]["tier"] == "native"
    assert not (out / "proxy_gaps").exists()
    assert bridge.queue.dropped == 0


def test_hybrid_e2e_divergent_proxy_emits_gap(tmp_path, corpus_bin):
    """Same planted finding against the deliberately divergent
    hybrid-safe binary: ``proxy_only`` + a gap report, never a
    silent drop."""
    out, bridge, events, c = _run_campaign(tmp_path, "test_safe")
    md5 = md5_hex(b"ABCD")
    assert c.get("hybrid_proxy_only", 0) >= 1
    ctv = {e["md5"]: e for e in events
           if e["type"] == "cross_tier_validate"}
    assert ctv[md5]["verdict"] == "proxy_only"
    gaps = [e for e in events if e["type"] == "proxy_gap"]
    assert gaps and gaps[0]["md5"] == md5
    report = json.load(open(out / "proxy_gaps" / f"{md5}.json"))
    assert report["schema"] == "kbz-proxy-gap-v1"
    assert report["binding"] == "test_safe"
    assert report["proxy"]["status"] == FUZZ_CRASH
    assert report["native"]["repro"] == 0
    # every enqueued finding got a verdict: nothing dropped
    assert bridge.validated == bridge.enqueued
    assert bridge.queue.dropped == 0


def test_message_train_replay_on_real_stdin(corpus_bin):
    """Framed sequences replay as stdin trains on a real binary: the
    concatenated train reaches the target (test-plain crashes when
    the messages concatenate to the magic)."""
    from killerbeez_tpu.hybrid.registry import (
        native_verdict, open_native,
    )
    spec = NativeSpec(argv=[corpus_bin("test-plain")],
                      delivery="stdin_train", m_max=4)
    binding = ProxyBinding(name="train", proxy_target="test",
                           native=spec)
    buf = frame_messages([b"AB", b"CD"], 4)
    target = open_native(spec)
    try:
        kind, _ = native_verdict(target, spec, binding.translate(buf))
        assert kind == FUZZ_CRASH
        benign = frame_messages([b"AB", b"CX"], 4)
        kind, _ = native_verdict(target, spec,
                                 binding.translate(benign))
        assert kind == FUZZ_NONE
    finally:
        target.close()


def test_cli_refuses_unknown_binding(tmp_path, capsys):
    """Stand-down at the CLI: an unknown binding exits 2 before any
    fuzzing happens."""
    from killerbeez_tpu.fuzzer.cli import main as cli_main
    seed = tmp_path / "seed"
    seed.write_bytes(b"hello")
    rc = cli_main(["file", "jit_harness", "havoc",
                   "-i", '{"target": "test"}', "-sf", str(seed),
                   "-n", "16", "-o", str(tmp_path / "out"),
                   "--hybrid", "no-such-binding"])
    assert rc == 2
    assert "no-such-binding" in capsys.readouterr().err
