"""TPU-hardware-gated Pallas regression tests (round-2 verdict item
4): all other kernel tests run in interpret mode on CPU, so a
Mosaic-lowering regression — the most fragile artifact in the repo —
would pass CI green.  These tests re-drive the real lowering whenever
a TPU is reachable and SKIP (visibly) when it is not.

The suite's conftest pins this process to a virtual CPU mesh, so the
on-chip checks run in a clean subprocess with the test platform
forcing stripped; the subprocess reports JSON on its last stdout
line.

Checks (the documented pre-commit ritual for kernel changes):
  (a) run_batch_pallas and fuzz_batch_pallas COMPILE on the chip;
  (b) bit-parity vs the XLA engine across every result field;
  (c) a conservative throughput floor on the flagship target, so a
      pathological-but-compiling regression (e.g. a relayout in the
      step loop) still fails loudly.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# conservative: the flagship kernel computes at ~1.9M execs/s on a
# v5e chip (round 5: i16 counts + stacked fetch dot), but the gate
# dispatches through a tunnel whose PER-DISPATCH overhead has
# measured anywhere from ~1ms to ~50ms across the day (best-of-3
# windows observed 290k-1.2M for the same binary kernel; longer
# windows measure SLOWER — deep dispatch queues throttle).  The
# floor therefore only catches order-of-magnitude lowering
# regressions (e.g. the 6-pass f32 dot decomposition); finer
# regressions are the parity+bench suite's job on stable hardware.
FLOOR_EXECS_PER_SEC = 150_000.0

_SUBPROCESS_CODE = r"""
import json, sys, time
import jax
try:
    devs = jax.devices()
except Exception as e:
    print(json.dumps({"skip": f"no devices: {e}"})); sys.exit(0)
if not devs or devs[0].platform != "tpu":
    print(json.dumps({"skip": f"no TPU ({devs and devs[0].platform})"}))
    sys.exit(0)

import numpy as np
import jax.numpy as jnp
from killerbeez_tpu.models import targets, targets_cgc
from killerbeez_tpu.models.vm import _run_batch_impl
from killerbeez_tpu.ops.vm_kernel import (
    LANE_TILE, dot_modes, fuzz_batch_pallas_2phase, havoc_words,
    run_batch_pallas,
)

prog = targets.get_target("tlvstack_vm")
seed = targets_cgc.tlvstack_vm_seed()
L = max(8, ((len(seed) + 7) // 8) * 8)
sb = np.zeros(L, np.uint8); sb[:len(seed)] = np.frombuffer(seed, np.uint8)
ins, tbl = jnp.asarray(prog.instrs), jnp.asarray(prog.edge_table)
sbj, slj = jnp.asarray(sb), jnp.int32(len(seed))
# the PRODUCT dtype config (exact-bf16 dots on guarded programs):
# parity below gates it bit-for-bit against the f32 XLA engine
dots = dot_modes(prog.instrs, prog.n_edges)
FIELDS = ("status", "exit_code", "counts", "steps", "path_hash")

# (a)+(b) fused kernel (two-phase, the product default) vs XLA engine
B = 4 * LANE_TILE
words = havoc_words(jax.random.fold_in(jax.random.key(0), 42), B)
res, bufs, lens = fuzz_batch_pallas_2phase(
    ins, tbl, sbj, slj, words, prog.mem_size, prog.max_steps,
    prog.n_edges, phase1_steps=-1, dots=dots)
ref = _run_batch_impl(ins, tbl, bufs, lens, prog.mem_size,
                      prog.max_steps, prog.n_edges, False)
for f in FIELDS:
    a, b = np.asarray(getattr(ref, f)), np.asarray(getattr(res, f))
    if not np.array_equal(a, b):
        print(json.dumps({"error": f"fused kernel parity: {f} diverged "
                          f"({int((a != b).sum())} lanes)"}))
        sys.exit(0)

# (b) plain VM kernel parity on the same mutants
out = run_batch_pallas(ins, tbl, bufs, lens, prog.mem_size,
                       prog.max_steps, prog.n_edges, dots=dots)
for f in FIELDS:
    a, b = np.asarray(getattr(ref, f)), np.asarray(getattr(out, f))
    if not np.array_equal(a, b):
        print(json.dumps({"error": f"vm kernel parity: {f} diverged"}))
        sys.exit(0)

# (b2) K-step superbatch path (lax.scan over the fused kernel, the
# CLI default): must compile on-chip and match K sequential fused
# steps bit-for-bit through the instrumentation layer
from killerbeez_tpu.instrumentation.base import pack_verdicts
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.mutators.factory import mutator_factory
import json as _json
K = 2
im = instrumentation_factory("jit_harness", _json.dumps(
    {"target": "tlvstack_vm", "engine": "pallas_fused",
     "novelty": "throughput"}))
i1 = instrumentation_factory("jit_harness", _json.dumps(
    {"target": "tlvstack_vm", "engine": "pallas_fused",
     "novelty": "throughput"}))
mm = mutator_factory("havoc", '{"seed": 9}', seed)
m1 = mutator_factory("havoc", '{"seed": 9}', seed)
its0 = mm.peek_iterations(B)
packed, mbufs, mlens, _c = im.run_batch_fused_multi(mm, its0, K)
mm.advance(K * B)
pk = np.asarray(packed)
for j in range(K):
    r1, b1, l1, _ = i1.run_batch_fused(m1, m1.peek_iterations(B))
    m1.advance(B)
    ref_pk = pack_verdicts(np.asarray(r1.statuses),
                           np.asarray(r1.new_paths),
                           np.asarray(r1.unique_crashes),
                           np.asarray(r1.unique_hangs))
    if not (np.array_equal(pk[j], ref_pk)
            and np.array_equal(np.asarray(mbufs[j]), np.asarray(b1))):
        print(_json.dumps({"error": f"superbatch step {j} diverged "
                           "from sequential fused steps"}))
        sys.exit(0)

# (c) throughput floor, steady-state (compiles are already cached)
Bf = 16384
wsteps = 10
ws = [havoc_words(jax.random.fold_in(jax.random.key(0), i), Bf)
      for i in range(wsteps + 1)]
jax.block_until_ready(ws)
r = fuzz_batch_pallas_2phase(ins, tbl, sbj, slj, ws[0], prog.mem_size,
                             prog.max_steps, prog.n_edges,
                             phase1_steps=-1, dots=dots)
jax.block_until_ready(r[0].status)
# best of 3 windows: a kernel regression depresses every window;
# tunnel/queue noise does not
rate = 0.0
for _ in range(3):
    t0 = time.time()
    for i in range(1, wsteps + 1):
        r = fuzz_batch_pallas_2phase(ins, tbl, sbj, slj, ws[i],
                                     prog.mem_size, prog.max_steps,
                                     prog.n_edges, phase1_steps=-1,
                                     dots=dots)
    jax.block_until_ready(r[0].status)
    rate = max(rate, Bf * wsteps / (time.time() - t0))
print(json.dumps({"ok": True, "execs_per_sec": rate,
                  "device": str(devs[0])}))
"""


def _run_on_chip():
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS",)}
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-c", _SUBPROCESS_CODE], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=560)
    last = (r.stdout.strip().splitlines() or ["{}"])[-1]
    try:
        return json.loads(last), r
    except json.JSONDecodeError:
        return {"error": f"no report (rc={r.returncode}): "
                         f"{r.stderr[-400:]}"}, r


@pytest.mark.slow  # ~90s real-chip subprocess (tunnel): run via the
# nightly lane or explicitly (`pytest tests/test_tpu_gate.py`) as the
# documented pre-commit ritual for kernel changes — keeping it out of
# the per-push lane keeps that lane < 5 min on a 1-core host.
def test_pallas_kernels_on_real_tpu():
    report, proc = _run_on_chip()
    if "skip" in report:
        pytest.skip(f"no TPU reachable: {report['skip']}")
    assert "error" not in report, report.get("error")
    assert report.get("ok"), f"on-chip run failed: {proc.stderr[-400:]}"
    assert report["execs_per_sec"] >= FLOOR_EXECS_PER_SEC, (
        f"fused kernel at {report['execs_per_sec']:.0f} execs/s — "
        f"below the {FLOOR_EXECS_PER_SEC:.0f} regression floor "
        f"on {report['device']}")
