"""KBVM + built-in target tests: crash/hang/coverage semantics."""

import jax.numpy as jnp
import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_NONE, FUZZ_RUNNING, MAP_SIZE
from killerbeez_tpu.models import compile_runner, run_batch, targets
from killerbeez_tpu.models.compiler import Assembler
from killerbeez_tpu.ops import build_bitmap, classify_counts, has_new_bits_seq


def run_inputs(program, byte_inputs):
    L = max(max((len(b) for b in byte_inputs), default=1), 1)
    L = ((L + 7) // 8) * 8
    buf = np.zeros((len(byte_inputs), L), dtype=np.uint8)
    lens = np.zeros(len(byte_inputs), dtype=np.int32)
    for i, b in enumerate(byte_inputs):
        buf[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        lens[i] = len(b)
    return run_batch(program, jnp.asarray(buf), jnp.asarray(lens))


def bitmaps_of(res, map_size=MAP_SIZE):
    return build_bitmap(res.edge_ids, res.edge_ids >= 0, map_size=map_size)


def test_target_registry():
    assert set(targets.target_names()) >= {"test", "hang", "libtest",
                                           "cgc_like"}
    with pytest.raises(ValueError, match="unknown target"):
        targets.get_target("nope")


def test_abcd_crashes_only_on_full_match():
    prog = targets.get_target("test")
    res = run_inputs(prog, [b"ABCD", b"ABC@", b"XXXX", b"AB", b"ABCDE"])
    st = np.asarray(res.status)
    assert st[0] == FUZZ_CRASH
    assert st[1] == FUZZ_NONE
    assert st[2] == FUZZ_NONE
    assert st[3] == FUZZ_NONE  # too short
    assert st[4] == FUZZ_CRASH  # prefix match still crashes


def test_coverage_deepens_with_prefix():
    prog = targets.get_target("test")
    seeds = [b"XXXX", b"AXXX", b"ABXX", b"ABCX", b"ABCD"]
    res = run_inputs(prog, seeds)
    cls = classify_counts(bitmaps_of(res))
    virgin = jnp.full((MAP_SIZE,), 0xFF, dtype=jnp.uint8)
    rets, _ = has_new_bits_seq(virgin, cls)
    # every deeper prefix discovers a brand-new edge
    assert list(np.asarray(rets)) == [2, 2, 2, 2, 2]
    # and re-running the same batch discovers nothing... from scratch:
    rets2, v = has_new_bits_seq(virgin, cls)
    rets3, _ = has_new_bits_seq(v, cls)
    assert list(np.asarray(rets3)) == [0, 0, 0, 0, 0]


def test_determinism():
    prog = targets.get_target("test")
    r1 = run_inputs(prog, [b"ABC@"] * 3)
    r2 = run_inputs(prog, [b"ABC@"] * 3)
    np.testing.assert_array_equal(np.asarray(r1.edge_ids),
                                  np.asarray(r2.edge_ids))
    # identical lanes produce identical edge streams
    e = np.asarray(r1.edge_ids)
    np.testing.assert_array_equal(e[0], e[1])


def test_hang_target():
    prog = targets.get_target("hang")
    res = run_inputs(prog, [b"Hello", b"no"])
    st = np.asarray(res.status)
    assert st[0] == FUZZ_RUNNING  # spun out the step budget -> hang
    assert st[1] == FUZZ_NONE
    assert int(res.steps[0]) == prog.max_steps
    assert int(res.steps[1]) < 20


def test_libtest_library_blocks():
    prog = targets.get_target("libtest")
    res = run_inputs(prog, [b"LX", b"LY", b"QQ"])
    bms = np.asarray(bitmaps_of(res))
    hit_counts = (bms != 0).sum(axis=1)
    # 'LX' runs lib deep path: strictly more edges than 'LY', which is
    # more than the non-library path
    assert hit_counts[0] > hit_counts[1] > hit_counts[2]


def test_cgc_like_parser():
    prog = targets.get_target("cgc_like")
    res = run_inputs(prog, [
        b"CG\x01\x04abcd",      # type1: checksum loop over 4 bytes
        b"CG\x02\x04\xff\x41",  # type2: OOB store index 255 -> crash
        b"CG\x02\x04\x05\x41",  # type2: in-bounds store -> fine
        b"CG\x03\x00",          # type3 echo
        b"ZZ\x01\x04abcd",      # bad magic
        b"C",                   # too short
    ])
    st = np.asarray(res.status)
    assert st[0] == FUZZ_NONE
    assert st[1] == FUZZ_CRASH
    assert st[2] == FUZZ_NONE
    assert st[3] == FUZZ_NONE
    assert st[4] == FUZZ_NONE and int(res.exit_code[4]) == 1
    assert st[5] == FUZZ_NONE and int(res.exit_code[5]) == 1


def test_cgc_like_loop_hit_counts():
    """The checksum loop should produce hit-count coverage: a longer
    payload hits the loop block more times -> different count bucket."""
    prog = targets.get_target("cgc_like")
    res = run_inputs(prog, [b"CG\x01\x02ab", b"CG\x01\x08abcdefgh"])
    cls = np.asarray(classify_counts(bitmaps_of(res)))
    virgin = jnp.full((MAP_SIZE,), 0xFF, dtype=jnp.uint8)
    rets, _ = has_new_bits_seq(virgin, jnp.asarray(cls))
    assert list(np.asarray(rets)) == [2, 1]  # same edges, new bucket


def test_declared_len_clamped():
    """Declared payload length beyond the real input must not hang or
    crash the parser (the clamp block)."""
    prog = targets.get_target("cgc_like")
    res = run_inputs(prog, [b"CG\x01\xffab"])
    assert int(res.status[0]) == FUZZ_NONE


def test_compile_runner_closure():
    prog = targets.get_target("test")
    runner = compile_runner(prog)
    buf = np.zeros((2, 8), dtype=np.uint8)
    buf[0, :4] = np.frombuffer(b"ABCD", dtype=np.uint8)
    buf[1, :4] = np.frombuffer(b"QQQQ", dtype=np.uint8)
    res = runner(jnp.asarray(buf), jnp.asarray([4, 4], dtype=np.int32))
    assert int(res.status[0]) == FUZZ_CRASH
    assert int(res.status[1]) == FUZZ_NONE


def test_assembler_errors():
    a = Assembler("x")
    with pytest.raises(ValueError, match="register"):
        a.ldi(9, 0)
    a.jmp("nowhere")
    with pytest.raises(ValueError, match="undefined label"):
        a.build()
    b = Assembler("y")
    b.label("l")
    with pytest.raises(ValueError, match="duplicate"):
        b.label("l")


def test_pc_out_of_range_crashes():
    a = Assembler("fallthrough")
    a.block()
    a.ldi(1, 5)  # no halt: pc walks off the end
    prog = a.build()
    res = run_inputs(prog, [b"x"])
    assert int(res.status[0]) == FUZZ_CRASH


def _brute_force_edge_pairs(instrs):
    """Independent reference for the static edge universe: enumerate
    every (prev block, next block) pair by recursive path walking
    from the entry and from each block head (no shared code with
    vm.compute_edges)."""
    from killerbeez_tpu.models.vm import (
        OP_BLOCK, OP_BR, OP_CRASH, OP_HALT, OP_JMP,
    )
    ni = len(instrs)
    block_pcs = [pc for pc in range(ni) if instrs[pc][0] == OP_BLOCK]
    idx = {pc: k for k, pc in enumerate(block_pcs)}
    pairs = set()

    def walk(from_idx, pc, seen):
        if pc < 0 or pc >= ni or pc in seen:
            return
        op, a, b, c = (int(x) for x in instrs[pc])
        if op == OP_BLOCK:
            pairs.add((from_idx, idx[pc]))
            return
        seen = seen | {pc}
        if op == OP_JMP:
            walk(from_idx, a, seen)
        elif op == OP_BR:
            walk(from_idx, c, seen)
            walk(from_idx, pc + 1, seen)
        elif op not in (OP_HALT, OP_CRASH):
            walk(from_idx, pc + 1, seen)

    walk(-1, 0, frozenset())
    for pc in block_pcs:
        walk(idx[pc], pc + 1, frozenset())
    return pairs


def _universe_pairs(prog):
    return set(zip(np.asarray(prog.edge_from).tolist(),
                   np.asarray(prog.edge_to).tolist()))


def test_compute_edges_branch_to_self_loop():
    """A branch back to its own block head is a (k, k) self-edge, and
    the engine's counts land on it once per taken iteration."""
    a = Assembler("selfloop", max_steps=64)
    a.block()                           # 0
    a.ldi(1, 0)
    a.label("head")
    a.block()                           # 1: loops on itself
    a.addi(1, 1, 1)
    a.ldi(2, 3)
    a.br("lt", 1, 2, "head")
    a.halt(0)
    prog = a.build()
    pairs = _universe_pairs(prog)
    assert (1, 1) in pairs
    assert pairs == _brute_force_edge_pairs(prog.instrs.tolist())
    res = run_inputs(prog, [b"x"])
    self_edge = int(prog.edge_table[2, 1])   # (from=1)+1 row, to=1
    # r1: 1, 2, 3 — the back branch is taken twice
    assert int(np.asarray(res.counts)[0, self_edge]) == 2
    assert int(res.status[0]) == FUZZ_NONE


def test_compute_edges_unreachable_tail_block():
    """Blocks jumped over by an unconditional jmp stay in the static
    universe (it is per-block local by design — kb-lint flags them),
    but never collect dynamic counts."""
    a = Assembler("unreach", max_steps=64)
    a.block()                           # 0
    a.jmp("end")
    a.block()                           # 1: unreachable tail
    a.label("end")
    a.block()                           # 2
    a.halt(0)
    prog = a.build()
    pairs = _universe_pairs(prog)
    assert (1, 2) in pairs              # edge FROM the dead block
    assert (0, 1) not in pairs          # but nothing reaches it
    assert pairs == _brute_force_edge_pairs(prog.instrs.tolist())
    res = run_inputs(prog, [b"x"])
    dead_edge = int(prog.edge_table[2, 2])
    assert int(np.asarray(res.counts)[0, dead_edge]) == 0
    from killerbeez_tpu.analysis import build_cfg
    assert build_cfg(prog).unreachable_blocks() == [1]


def test_compute_edges_first_instruction_not_block():
    """Instructions before the first OP_BLOCK belong to the entry
    path: the first block's edge is (-1, 0) with slot == its raw id
    (prev_loc starts at 0)."""
    a = Assembler("latehead", max_steps=32)
    a.ldi(1, 0)
    a.ldb(2, 1)
    a.block()                           # 0: first block, 2 instrs in
    a.halt(0)
    prog = a.build()
    pairs = _universe_pairs(prog)
    assert pairs == {(-1, 0)}
    assert pairs == _brute_force_edge_pairs(prog.instrs.tolist())
    assert int(prog.edge_slot[0]) == prog.block_ids[0]
    res = run_inputs(prog, [b"x"])
    entry_edge = int(prog.edge_table[0, 0])
    assert int(np.asarray(res.counts)[0, entry_edge]) == 1


def test_compute_edges_matches_brute_force_on_builtins():
    for name in targets.target_names():
        prog = targets.get_target(name)
        assert _universe_pairs(prog) == \
            _brute_force_edge_pairs(prog.instrs.tolist()), name


def test_single_lane_reference_engine_parity(rng):
    """vm._run_one is the readable single-lane reference the batched
    one-hot engine is built against: statuses, exit codes, edge
    streams, counts and path hashes must agree lane-for-lane."""
    import jax
    from killerbeez_tpu.models.vm import _run_batch_impl, _run_one

    for name in ("test", "cgc_like", "tlvstack_vm"):
        prog = targets.get_target(name)
        B, L = 16, 32
        inputs = rng.integers(0, 256, (B, L)).astype(np.uint8)
        from killerbeez_tpu.models import targets_cgc
        seed_fn = targets_cgc.VM_SEEDS.get(name)
        seed = seed_fn[0]() if seed_fn else b"ABC@"
        inputs[0, :len(seed)] = np.frombuffer(seed, np.uint8)
        lengths = rng.integers(1, L + 1, B).astype(np.int32)
        instrs = jnp.asarray(prog.instrs)
        table = jnp.asarray(prog.edge_table)
        batched = _run_batch_impl(instrs, table, jnp.asarray(inputs),
                                  jnp.asarray(lengths), prog.mem_size,
                                  prog.max_steps, prog.n_edges, True)
        one = jax.vmap(
            lambda b, ln: _run_one(instrs, table, prog.n_edges,
                                   prog.mem_size, prog.max_steps, b, ln)
        )(jnp.asarray(inputs), jnp.asarray(lengths))
        for f in ("status", "exit_code", "counts", "steps",
                  "path_hash", "edge_ids"):
            np.testing.assert_array_equal(
                np.asarray(getattr(batched, f)),
                np.asarray(getattr(one, f)),
                err_msg=f"{name}: {f} diverged")
