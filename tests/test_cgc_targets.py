"""CGC-grade realistic targets (VERDICT "Realistic targets"): the
native parsers (corpus/{imgparse,tlvstack,rledec}.c) and their KBVM
ports (models/targets_cgc.py).

Contract per target: the seed exercises the happy path without
crashing, the crash reproducer deterministically triggers the planted
memory bug, and (KBVM) a havoc run from a near-miss seed finds a
crash on-device.
"""

import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_NONE
from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.fuzzer.loop import Fuzzer
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.models import targets
from killerbeez_tpu.models import targets_cgc
from killerbeez_tpu.models.vm import run_batch
from killerbeez_tpu.mutators.factory import mutator_factory


def _run_one(prog, data: bytes):
    buf = np.zeros((1, max(len(data), 8)), np.uint8)
    buf[0, :len(data)] = np.frombuffer(data, np.uint8)
    return run_batch(prog, buf, np.array([len(data)], np.int32))


# ---------------- KBVM ports ----------------

@pytest.mark.parametrize("name", sorted(targets_cgc.VM_SEEDS))
def test_vm_seed_runs_clean(name):
    prog = targets.get_target(name)
    seed_fn, _ = targets_cgc.VM_SEEDS[name]
    res = _run_one(prog, seed_fn())
    assert int(res.status[0]) == FUZZ_NONE
    assert int(res.exit_code[0]) == 0        # happy path, not "bad"


@pytest.mark.parametrize("name", sorted(targets_cgc.VM_SEEDS))
def test_vm_crash_repro(name):
    prog = targets.get_target(name)
    _, crash_fn = targets_cgc.VM_SEEDS[name]
    res = _run_one(prog, crash_fn())
    assert int(res.status[0]) == FUZZ_CRASH


def test_vm_block_scale():
    """The flagship bench target must not be a toy: CGC-scale block
    counts so coverage doesn't saturate in one batch."""
    assert targets.get_target("tlvstack_vm").n_blocks >= 100
    assert targets.get_target("imgparse_vm").n_blocks >= 30
    assert targets.get_target("rledec_vm").n_blocks >= 30


def test_vm_seed_covers_many_blocks():
    """The seed input alone should walk a nontrivial block set (loops,
    handlers), giving the fuzzer a graded landscape."""
    prog = targets.get_target("tlvstack_vm")
    seed_fn, _ = targets_cgc.VM_SEEDS["tlvstack_vm"]
    res = _run_one(prog, seed_fn())
    edges = np.asarray(res.edge_ids[0])
    assert (edges >= 0).sum() >= 20


def test_vm_bad_magic_distinct_exit():
    prog = targets.get_target("tlvstack_vm")
    res = _run_one(prog, b"NOPE")
    assert int(res.status[0]) == FUZZ_NONE
    assert int(res.exit_code[0]) == 1        # "bad" exit


def test_priv_tier_needs_keyword():
    """PRIV (0x0d) without a prior KEY unlock must take the bad exit."""
    prog = targets.get_target("tlvstack_vm")
    res = _run_one(prog, b"STK1" + bytes([0x0D, 3]))
    assert int(res.exit_code[0]) == 1
    res = _run_one(prog, b"STK1" + bytes([0x0C, 0]) +
                   targets_cgc._KEYWORD + bytes([0x0D, 3, 0x0B, 0]))
    assert int(res.exit_code[0]) == 0


def test_imgparse_vm_checksum_enforced():
    prog = targets.get_target("imgparse_vm")
    good = targets_cgc.imgparse_vm_seed()
    bad = bytearray(good)
    bad[-1] ^= 0xFF                           # corrupt E-chunk cksum
    res = _run_one(prog, bytes(bad))
    assert int(res.exit_code[0]) == 1


def test_havoc_finds_tlvstack_vm_bug(tmp_path):
    """One bit from the planted SIND bug: the crash repro with its
    final opcode turned into HALT (0x0b; the bug op is 0x0a) — havoc
    must flip it back and surface the crash on-device.  (imgparse_vm's
    bugs sit behind per-chunk checksums, deliberately out of reach of
    dumb byte mutation — the realistic CGC property.)"""
    seed = bytearray(targets_cgc.tlvstack_vm_crash())
    assert seed[-2] == 0x0A
    seed[-2] = 0x0B                              # SIND -> HALT
    instr = instrumentation_factory(
        "jit_harness", '{"target": "tlvstack_vm", '
        '"novelty": "throughput"}')
    mut = mutator_factory("havoc", '{"seed": 5}', bytes(seed))
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=512)
    stats = fz.run(8192)
    assert stats.crashes > 0
    assert stats.new_paths > 0


def test_vm_and_native_crash_repros_stay_in_sync(corpus_seeds):
    """corpus/seeds.py (native, jax-free standalone script) and
    targets_cgc (KBVM) deliberately define the tlvstack crash bytes
    twice; this pins them byte-identical so the 'same planted bug'
    claim can't silently desynchronize."""
    assert corpus_seeds.tlvstack_crash() == targets_cgc.tlvstack_vm_crash()
    assert corpus_seeds.chunk(b"H", b"\x01\x02") == \
        targets_cgc._chunk(b"H", b"\x01\x02")


# ---------------- native parsers ----------------

NATIVE = ["imgparse", "tlvstack", "rledec"]


@pytest.fixture(scope="module")
def corpus_seeds():
    """The corpus/seeds.py module (seed + crash generators)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "corpus_seeds", os.path.join(os.path.dirname(__file__),
                                     "..", "corpus", "seeds.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("name", NATIVE)
def test_native_seed_and_crash(corpus_bin, corpus_seeds, name):
    from killerbeez_tpu.native.exec_backend import ExecTarget, classify
    seed = [v for k, v in corpus_seeds.SEEDS.items()
            if k.startswith(name + ".")][0]()
    crash = [v for k, v in corpus_seeds.SEEDS.items()
             if k.startswith(name + "_crash")][0]()
    with ExecTarget([corpus_bin(name)], use_stdin=True,
                    use_forkserver=True, coverage=True,
                    timeout=5.0) as t:
        assert classify(t.run(seed))[0] == FUZZ_NONE
        assert classify(t.run(crash))[0] == FUZZ_CRASH


@pytest.mark.parametrize("name", NATIVE)
def test_native_coverage_depth(corpus_bin, corpus_seeds, name):
    """A valid seed must touch clearly more edges than garbage input —
    the parsers have real depth for coverage to climb."""
    from killerbeez_tpu.native.exec_backend import ExecTarget
    seed = [v for k, v in corpus_seeds.SEEDS.items()
            if k.startswith(name + ".")][0]()
    with ExecTarget([corpus_bin(name)], use_stdin=True,
                    use_forkserver=True, coverage=True) as t:
        t.clear_trace()
        t.run(b"\xff\xff")
        garbage_edges = int((t.trace_bits() != 0).sum())
        t.clear_trace()
        t.run(seed)
        seed_edges = int((t.trace_bits() != 0).sum())
    assert seed_edges > garbage_edges + 5
