"""Fleet observatory tests (manager/fleet.py, telemetry/openmetrics,
kb-fleet, kb-timeline --fleet) — the two-worker e2e the CI fleet lane
runs: register -> heartbeat -> one worker dies -> ``worker_dead``
event + worker_death alert within the configured timeout ->
``/api/fleet`` and ``/metrics`` reflect it (with the ``/metrics``
body checked by the strict OpenMetrics parser), plus deterministic
alert-rule / time-series / cursor coverage driven through manual
monitor ticks with a synthetic clock.
"""

import json
import threading
import time
import urllib.request

import pytest

from killerbeez_tpu.manager import ManagerDB
from killerbeez_tpu.manager.api import ManagerServer
from killerbeez_tpu.manager.fleet import (
    ALERT_RULES, FleetConfig, FleetMonitor, classify,
    render_fleet_metrics,
)
from killerbeez_tpu.telemetry import MetricsRegistry
from killerbeez_tpu.telemetry.openmetrics import (
    render_snapshot, sanitize_metric_name,
)
from openmetrics_parser import parse_openmetrics, sample_value

FAST = dict(stale_after=0.3, dead_after=0.6, monitor_interval=0.05,
            series_interval=0.1, plateau_after=30.0, stall_after=60.0,
            crash_spike_count=3, crash_spike_window=5.0)


@pytest.fixture
def server():
    s = ManagerServer(port=0, fleet=FleetConfig(**FAST))
    s.start()
    yield s
    s.stop()


def _get(server, path, raw=False):
    url = f"http://127.0.0.1:{server.port}{path}"
    with urllib.request.urlopen(url, timeout=10) as resp:
        body = resp.read()
        return body.decode() if raw else json.loads(body)


def _post(server, path, payload):
    url = f"http://127.0.0.1:{server.port}{path}"
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def _snap(execs, paths=0, uc=0, crashes=None, t=None, drops=0):
    return {"t": time.time() if t is None else t, "start_time": 0.0,
            "elapsed": 10.0,
            "counters": {"execs": execs, "new_paths": paths,
                         "crashes": (uc if crashes is None
                                     else crashes),
                         "unique_crashes": uc,
                         "findings_ring_drops": drops},
            "gauges": {"corpus_seen": paths},
            "rates": {"execs": {"rate": 100.0, "weight": 1.0}},
            "derived": {"execs_per_sec": 10.0,
                        "execs_per_sec_ema": 100.0}}


# -- OpenMetrics rendering ---------------------------------------------


def test_openmetrics_roundtrip_through_strict_parser():
    """Every registry series kind survives render -> strict parse
    with its value intact (the satellite round-trip gate)."""
    reg = MetricsRegistry()
    reg.count("execs", 4096)
    reg.count("9weird.name", 3)          # needs sanitization
    reg.gauge("corpus_seen", 17)
    reg.rate("execs", 100)
    for v in (1e-5, 3e-3, 0.4, 2.0):
        reg.observe("triage", v)
    text = render_snapshot(reg.snapshot(), labels={"worker": "w1"})
    fams = parse_openmetrics(text)
    lab = {"worker": "w1"}
    assert fams["kbz_execs"]["type"] == "counter"
    assert sample_value(fams, "kbz_execs", "kbz_execs_total",
                        lab) == 4096
    assert sample_value(fams, "kbz_corpus_seen", "kbz_corpus_seen",
                        lab) == 17
    assert fams["kbz_execs_rate"]["type"] == "gauge"
    hist = fams["kbz_triage_duration_seconds"]
    assert hist["type"] == "histogram"
    counts = [v for n, la, v in hist["samples"]
              if n.endswith("_count")]
    assert counts == [4]
    total = [v for n, la, v in hist["samples"] if n.endswith("_sum")]
    assert total[0] == pytest.approx(1e-5 + 3e-3 + 0.4 + 2.0)


def test_openmetrics_label_escaping_and_sanitization():
    nasty = 'w"1\n\\end'
    text = render_snapshot({"counters": {"execs": 1}},
                           labels={"bad label": nasty})
    fams = parse_openmetrics(text)
    assert sample_value(fams, "kbz_execs", "kbz_execs_total",
                        {"bad_label": nasty}) == 1
    assert sanitize_metric_name("9a-b.c") == "_9a_b_c"


def test_openmetrics_parser_is_actually_strict():
    """The conformance oracle rejects malformed expositions — a
    broken renderer can't pass by accident."""
    good = render_snapshot({"counters": {"execs": 1}})
    parse_openmetrics(good)              # sanity
    for bad in (
        good.replace("# EOF\n", ""),             # missing EOF
        good.replace("kbz_execs_total", "kbz_execs"),  # bad suffix
        "kbz_x 1\n# EOF\n",                      # sample before TYPE
        "# TYPE h histogram\nh_bucket{le=\"1.0\"} 1\n"
        "h_count 1\nh_sum 1\n# EOF\n",           # no +Inf bucket
        "# TYPE h histogram\nh_bucket{le=\"1.0\"} 5\n"
        "h_bucket{le=\"+Inf\"} 3\nh_count 3\nh_sum 1\n"
        "# EOF\n",                               # decreasing buckets
        "# TYPE c counter\nc_total 1\nc_total 2\n# EOF\n",  # dup
    ):
        with pytest.raises(ValueError):
            parse_openmetrics(bad)


# -- health classification ---------------------------------------------


def test_classify_thresholds():
    cfg = FleetConfig(stale_after=10, dead_after=30)
    assert classify(0.0, cfg) == "healthy"
    assert classify(9.9, cfg) == "healthy"
    assert classify(10.0, cfg) == "stale"
    assert classify(29.9, cfg) == "stale"
    assert classify(30.0, cfg) == "dead"


# -- two-worker e2e (the CI fleet lane's acceptance gate) --------------


def test_two_worker_e2e_death_alert_and_metrics(server):
    """Register two workers, kill one: within the configured timeout
    the manager classifies it dead, emits worker_stale/worker_dead
    into the campaign stream, raises the worker_death alert, and
    both /api/fleet and a conformant /metrics scrape reflect it;
    reviving the worker emits worker_returned and clears the
    alert."""
    _post(server, "/api/stats/7",
          {"worker": "w1", "snapshot": _snap(1000, 5),
           "meta": {"pid": 111, "host": "a"}})
    _post(server, "/api/stats/7",
          {"worker": "w2", "snapshot": _snap(500, 3),
           "meta": {"pid": 222, "host": "b"}})

    halt = threading.Event()

    def keep_w1_alive():
        while not halt.wait(0.1):
            _post(server, "/api/stats/7",
                  {"worker": "w1", "snapshot": _snap(1000, 5)})

    t = threading.Thread(target=keep_w1_alive, daemon=True)
    t.start()
    try:
        # poll until the FULL expected state holds — a loaded runner
        # can momentarily delay w1's keep-alive past the 0.3s stale
        # threshold, so breaking on w2's death alone would flake
        deadline = time.time() + 10     # >> dead_after (0.6s)
        fv = None
        while time.time() < deadline:
            fv = _get(server, "/api/fleet/7")
            if (fv["workers"]["w2"]["status"] == "dead"
                    and fv["workers"]["w1"]["status"] == "healthy"
                    and any(a["alert"] == "worker_death"
                            and a["active"] for a in fv["alerts"])):
                break
            time.sleep(0.05)
        assert fv["workers"]["w2"]["status"] == "dead"
        assert fv["workers"]["w1"]["status"] == "healthy"
        assert fv["counts"] == {"healthy": 1, "stale": 0, "dead": 1}
        assert fv["workers"]["w2"]["meta"] == {"pid": 222,
                                              "host": "b"}
        death = [a for a in fv["alerts"]
                 if a["alert"] == "worker_death"][0]
        assert death["active"] is True
        assert death["details"]["dead_workers"] == ["w2"]
        # merged fleet snapshot carries the health fields
        assert fv["merged"]["health"]["w2"]["status"] == "dead"
        assert fv["merged"]["counters"]["execs"] == 1500

        # the event stream has the manager-origin records, cursor-
        # readable exactly like worker events
        ev = _get(server, "/api/events/7")
        types = [(e["worker"], e["event"]["type"])
                 for e in ev["events"]]
        assert ("_manager", "worker_dead") in types
        assert ("_manager", "worker_stale") in types
        alert_evs = [e["event"] for e in ev["events"]
                     if e["event"]["type"] == "alert"]
        assert any(e["alert"] == "worker_death" and e["active"]
                   for e in alert_evs)

        # /metrics: strict-parse the scrape, check the gauges
        text = _get(server, "/metrics", raw=True)
        fams = parse_openmetrics(text)
        assert sample_value(fams, "kbz_worker_up", "kbz_worker_up",
                            {"campaign": "7", "worker": "w2"}) == 0
        assert sample_value(fams, "kbz_worker_up", "kbz_worker_up",
                            {"campaign": "7", "worker": "w1"}) == 1
        assert sample_value(fams, "kbz_alert_active",
                            "kbz_alert_active",
                            {"campaign": "7",
                             "alert": "worker_death"}) == 1
        assert sample_value(fams, "kbz_fleet_workers",
                            "kbz_fleet_workers",
                            {"campaign": "7", "status": "dead"}) == 1
        # fleet fold labeled {campaign} only, per-worker labeled both
        assert sample_value(fams, "kbz_fleet_execs",
                            "kbz_fleet_execs_total",
                            {"campaign": "7"}) == 1500
        assert sample_value(fams, "kbz_execs", "kbz_execs_total",
                            {"campaign": "7", "worker": "w2"}) == 500

        # kb-fleet sees one healthy + one dead worker
        from killerbeez_tpu.tools import fleet_tool
        import io
        from contextlib import redirect_stdout
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = fleet_tool.main(
                [f"http://127.0.0.1:{server.port}",
                 "--campaign", "7", "--json"])
        assert rc == 0
        body = json.loads(buf.getvalue())
        statuses = {w: v["status"]
                    for w, v in body["workers"].items()}
        assert statuses == {"w1": "healthy", "w2": "dead"}
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = fleet_tool.main(
                [f"http://127.0.0.1:{server.port}",
                 "--campaign", "7"])
        assert rc == 0
        table = buf.getvalue()
        assert "worker_death active" in table
        assert "dead" in table and "healthy" in table

        # revive w2: worker_returned lands, the alert clears
        _post(server, "/api/stats/7",
              {"worker": "w2", "snapshot": _snap(600, 3)})
        deadline = time.time() + 10
        cleared = False
        while time.time() < deadline:
            fv = _get(server, "/api/fleet/7")
            death = [a for a in fv["alerts"]
                     if a["alert"] == "worker_death"][0]
            if not death["active"] \
                    and fv["workers"]["w2"]["status"] == "healthy":
                cleared = True
                break
            time.sleep(0.05)
        assert cleared
        ev = _get(server, "/api/events/7")
        assert ("_manager", "worker_returned") in [
            (e["worker"], e["event"]["type"]) for e in ev["events"]]
    finally:
        halt.set()
        t.join(timeout=2)


def test_kb_timeline_fleet_merges_worker_streams(server, capsys):
    """--fleet merges two workers' forwarded streams plus the
    manager's records onto one wall-clock axis."""
    t0 = time.time()
    _post(server, "/api/events/7", {"worker": "w1", "events": [
        {"v": 1, "seq": 0, "t": t0, "type": "crash", "md5": "aa"},
        {"v": 1, "seq": 1, "t": t0 + 2.0, "type": "plateau"}]})
    _post(server, "/api/events/7", {"worker": "w2", "events": [
        {"v": 1, "seq": 0, "t": t0 + 1.0, "type": "hang",
         "md5": "bb"}]})
    server.db.add_manager_event("7", "worker_dead", worker="w2",
                                now=t0 + 3.0)
    from killerbeez_tpu.tools import timeline_tool
    rc = timeline_tool.main(
        ["--fleet", f"http://127.0.0.1:{server.port}",
         "--campaign", "7", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    rep = out["report"]
    assert rep["total"] == 4
    assert rep["counts"] == {"crash": 1, "plateau": 1, "hang": 1,
                             "worker_dead": 1}
    # the worker_dead record names its subject worker, so the death
    # marker lands on w2's own lane
    assert set(rep["workers"]) == {"w1", "w2"}
    assert rep["workers"]["w2"]["worker_dead"] == 1
    assert rep["window_s"] == pytest.approx(3.0, abs=0.01)
    # events are total-ordered on the shared wall clock
    ts = [e["t"] for e in out["events"]]
    assert ts == sorted(ts)
    # human rendering: one lane per stream
    rc = timeline_tool.main(
        ["--fleet", f"http://127.0.0.1:{server.port}",
         "--campaign", "7"])
    assert rc == 0
    txt = capsys.readouterr().out
    assert "w1" in txt and "w2" in txt
    # unknown campaign -> loud nonzero
    rc = timeline_tool.main(
        ["--fleet", f"http://127.0.0.1:{server.port}",
         "--campaign", "nope"])
    assert rc == 1


# -- deterministic monitor coverage (manual ticks, synthetic clock) ----


def _mk_monitor(**over):
    cfg = FleetConfig(**{**FAST, **over, "monitor_interval": 0.0})
    db = ManagerDB()
    return db, FleetMonitor(db, cfg)


def test_fleet_series_cursor_pagination():
    db, mon = _mk_monitor(series_interval=1.0)
    now = 1000.0
    db.note_fleet_worker("c", "w1", now=now)
    for i in range(5):
        db.upsert_campaign_stats("c", "w1",
                                 _snap(100 * (i + 1), i, t=now))
        mon.tick(now=now)
        now += 1.0
    rows = db.get_fleet_series("c")
    assert len(rows) == 5
    ids = [r["id"] for r in rows]
    assert ids == sorted(ids)
    assert [r["execs"] for r in rows] == [100, 200, 300, 400, 500]
    # cursor: only samples past the given id come back
    tail = db.get_fleet_series("c", since_id=ids[2])
    assert [r["id"] for r in tail] == ids[3:]
    assert db.fleet_series_latest_id("c") == ids[-1]
    # limit caps the page
    page = db.get_fleet_series("c", since_id=0, limit=2)
    assert [r["id"] for r in page] == ids[:2]
    # history survives worker churn: the dead worker's last totals
    # stay in the series
    sample = rows[-1]
    assert sample["n_workers"] == 1
    assert sample["new_paths"] == 4


def test_alert_rules_plateau_spike_stall():
    db, mon = _mk_monitor(plateau_after=10.0, stall_after=20.0,
                          crash_spike_count=3,
                          crash_spike_window=5.0,
                          series_interval=1e9)
    now = 1000.0
    db.note_fleet_worker("c", "w1", now=now)

    def beat(execs, paths, uc, t):
        db.note_fleet_worker("c", "w1", now=t)
        db.upsert_campaign_stats("c", "w1",
                                 _snap(execs, paths, uc=uc, t=t))

    beat(100, 1, 0, now)
    mon.tick(now=now)
    assert not any(a["active"] for a in mon.alerts("c"))
    # paths flat while execs advance: plateau at +10s, stall at +20s
    for dt in (5.0, 9.0):
        beat(100 + int(dt * 10), 1, 0, now + dt)
        mon.tick(now=now + dt)
    assert not [a for a in mon.alerts("c")
                if a["alert"] == "fleet_plateau" and a["active"]]
    beat(300, 1, 0, now + 11.0)
    mon.tick(now=now + 11.0)
    active = {a["alert"] for a in mon.alerts("c") if a["active"]}
    assert "fleet_plateau" in active
    assert "coverage_stall" not in active
    beat(400, 1, 0, now + 21.0)
    mon.tick(now=now + 21.0)
    active = {a["alert"] for a in mon.alerts("c") if a["active"]}
    assert {"fleet_plateau", "coverage_stall"} <= active
    # a new path clears both
    beat(500, 2, 0, now + 22.0)
    mon.tick(now=now + 22.0)
    active = {a["alert"] for a in mon.alerts("c") if a["active"]}
    assert "fleet_plateau" not in active
    assert "coverage_stall" not in active
    # crash spike: 3 unique crashes inside the 5s window
    beat(600, 3, 1, now + 23.0)
    mon.tick(now=now + 23.0)
    beat(700, 4, 4, now + 24.0)
    mon.tick(now=now + 24.0)
    spike = [a for a in mon.alerts("c")
             if a["alert"] == "crash_spike"][0]
    assert spike["active"]
    # rising edge emitted exactly one active=True alert event
    evs = [json.loads(r["payload"])
           for r in db._rows("SELECT payload FROM campaign_events "
                             "WHERE campaign='c'")]
    spikes = [e for e in evs if e["type"] == "alert"
              and e.get("alert") == "crash_spike"
              and e.get("active")]
    assert len(spikes) == 1
    # window slides past the spike -> clears, with a clearing event
    beat(800, 5, 4, now + 31.0)
    mon.tick(now=now + 31.0)
    spike = [a for a in mon.alerts("c")
             if a["alert"] == "crash_spike"][0]
    assert not spike["active"]


def test_alert_rule_findings_drop_edges():
    """findings_ring_drops is counted but was never alerted: the
    findings_drop rule fires when the fleet's counter MOVES, stays
    active while drops keep landing, and clears after a quiet
    drops_window — and a manager restart seeing a stale lifetime
    total only baselines (no re-alarm on drops that stopped hours
    ago)."""
    db, mon = _mk_monitor(drops_window=20.0, series_interval=1e9)
    now = 1000.0
    db.note_fleet_worker("c", "w1", now=now)

    def beat(execs, drops, t):
        db.note_fleet_worker("c", "w1", now=t)
        db.upsert_campaign_stats("c", "w1",
                                 _snap(execs, 1, t=t, drops=drops))

    # first observation carries a nonzero lifetime total: baseline
    # only — the drops may predate this monitor's lifetime
    beat(100, 5, now)
    mon.tick(now=now)
    assert not [a for a in mon.alerts("c")
                if a["alert"] == "findings_drop" and a["active"]]
    # the counter MOVES: rising edge, one active=True event
    beat(200, 9, now + 5.0)
    mon.tick(now=now + 5.0)
    drop = [a for a in mon.alerts("c")
            if a["alert"] == "findings_drop"][0]
    assert drop["active"]
    assert drop["details"]["findings_ring_drops_total"] == 9
    # still active inside the window, no movement
    beat(300, 9, now + 15.0)
    mon.tick(now=now + 15.0)
    assert [a for a in mon.alerts("c")
            if a["alert"] == "findings_drop"][0]["active"]
    # a quiet drops_window clears it, with a clearing event
    beat(400, 9, now + 26.0)
    mon.tick(now=now + 26.0)
    assert not [a for a in mon.alerts("c")
                if a["alert"] == "findings_drop"][0]["active"]
    # /api/fleet body + /metrics exposition both carry the rule
    # (checked BEFORE the decrease beat below overwrites the worker
    # snapshot — the stat summary reports the CURRENT heartbeat)
    from killerbeez_tpu.manager.fleet import fleet_view
    body = fleet_view(db, mon.cfg, "c", monitor=mon, now=now + 26.0)
    assert "findings_drop" in {a["alert"] for a in body["alerts"]}
    assert body["workers"]["w1"]["stats"][
        "findings_ring_drops"] == 9
    text = render_fleet_metrics(db, mon.cfg, mon, now=now + 26.0)
    fams = parse_openmetrics(text)
    assert "findings_drop" in {
        lab["alert"] for _, lab, _ in
        fams["kbz_alert_active"]["samples"]}
    # a DECREASE of the merged total (a worker restarted/retired and
    # its monotone counter reset) is not a new drop: no re-fire
    beat(500, 4, now + 27.0)
    mon.tick(now=now + 27.0)
    assert not [a for a in mon.alerts("c")
                if a["alert"] == "findings_drop"][0]["active"]
    evs = [json.loads(r["payload"])
           for r in db._rows("SELECT payload FROM campaign_events "
                             "WHERE campaign='c'")]
    fires = [e for e in evs if e["type"] == "alert"
             and e.get("alert") == "findings_drop"]
    assert [e.get("active") for e in fires] == [True, False]


def test_manager_events_monotone_seq_and_dedup():
    db = ManagerDB()
    r1 = db.add_manager_event("c", "worker_dead", worker="w1")
    r2 = db.add_manager_event("c", "alert", alert="worker_death",
                              active=True)
    assert (r1["seq"], r2["seq"]) == (0, 1)
    assert r1["v"] >= 1 and "t" in r1
    rows = db.get_campaign_events("c")
    assert [r["event"]["seq"] for r in rows] == [0, 1]
    assert all(r["worker"] == "_manager" for r in rows)
    # worker streams are independent of the manager's seq space
    db.add_campaign_events("c", "w1", [
        {"v": 1, "seq": 0, "t": 5.0, "type": "crash"}])
    assert len(db.get_campaign_events("c")) == 3


def test_note_fleet_worker_registration_and_return():
    db = ManagerDB()
    assert db.note_fleet_worker("c", "w1", now=100.0) is None
    row = db.get_fleet_workers("c")[0]
    assert row["first_seen"] == 100.0
    assert row["last_seen"] == 100.0 and row["beats"] == 1
    db.set_fleet_worker_status("c", "w1", "dead")
    assert db.note_fleet_worker("c", "w1", now=200.0) == "dead"
    row = db.get_fleet_workers("c")[0]
    assert row["status"] == "healthy"
    assert row["first_seen"] == 100.0    # registration time sticks
    assert row["last_seen"] == 200.0 and row["beats"] == 2


def test_fleet_series_retention_cap():
    """The history table stays bounded: the oldest rows beyond
    max_rows are pruned at insert, cursors stay valid (ids only
    disappear from the old end)."""
    db = ManagerDB()
    ids = [db.add_fleet_sample("c", {"t": float(i), "execs": i},
                               max_rows=3) for i in range(7)]
    rows = db.get_fleet_series("c")
    assert [r["id"] for r in rows] == ids[-3:]
    assert [r["execs"] for r in rows] == [4, 5, 6]
    # other campaigns are untouched by the prune
    db.add_fleet_sample("other", {"t": 0.0})
    db.add_fleet_sample("c", {"t": 8.0}, max_rows=3)
    assert len(db.get_fleet_series("other")) == 1


def test_status_escalation_loses_to_racing_heartbeat():
    """The monitor's conditional status write: a heartbeat bumping
    last_seen between the tick's read and its write wins — no
    spurious worker_stale/worker_dead lands in the stream."""
    db = ManagerDB()
    db.note_fleet_worker("c", "w1", now=100.0)
    row = db.get_fleet_workers("c")[0]           # the tick's read
    db.note_fleet_worker("c", "w1", now=200.0)   # beat races in
    assert db.set_fleet_worker_status(
        "c", "w1", "dead", expect_last_seen=row["last_seen"]) \
        is False
    assert db.get_fleet_workers("c")[0]["status"] == "healthy"
    # unraced write applies
    row = db.get_fleet_workers("c")[0]
    assert db.set_fleet_worker_status(
        "c", "w1", "stale", expect_last_seen=row["last_seen"])
    assert db.get_fleet_workers("c")[0]["status"] == "stale"


def test_kb_fleet_json_gates_on_empty_campaign(server, capsys):
    """--json is the scripting mode: an unknown/empty campaign must
    exit nonzero there too (the documented gating contract)."""
    from killerbeez_tpu.tools import fleet_tool
    rc = fleet_tool.main([f"http://127.0.0.1:{server.port}",
                          "--campaign", "ghost", "--json"])
    assert rc == 1
    assert "no workers seen" in capsys.readouterr().err


def test_worker_retirement_clears_finished_campaigns():
    """A finished campaign's workers retire after --retire-after:
    the registry row and heartbeat snapshot go away (bounded
    /metrics cardinality), the worker_death alert clears instead of
    latching forever, and fleet_series history survives."""
    db, mon = _mk_monitor(retire_after=100.0, series_interval=1.0)
    db.note_fleet_worker("c", "w1", now=1000.0)
    db.upsert_campaign_stats("c", "w1", _snap(10, 1, t=1000.0))
    mon.tick(now=1000.0)
    assert len(db.get_fleet_series("c")) == 1
    mon.tick(now=1010.0)                 # worker now dead (0.7s cfg)
    assert [a for a in mon.alerts("c")
            if a["alert"] == "worker_death"][0]["active"]
    mon.tick(now=1200.0)                 # past retire_after
    assert db.get_fleet_workers("c") == []
    assert db.get_campaign_stats("c") == []
    assert not [a for a in mon.alerts("c")
                if a["alert"] == "worker_death" and a["active"]]
    # history outlives the workers
    assert len(db.get_fleet_series("c")) >= 1
    text = render_fleet_metrics(db, mon.cfg, mon, now=1200.0)
    assert 'worker="w1"' not in text


def test_all_alert_rules_exposed_on_metrics():
    """Every declarative rule gets a kbz_alert_active series (zeros
    included) so dashboards can alert on absence too."""
    db, mon = _mk_monitor()
    db.note_fleet_worker("c", "w1", now=1000.0)
    db.upsert_campaign_stats("c", "w1", _snap(10, 1, t=1000.0))
    mon.tick(now=1000.0)
    text = render_fleet_metrics(db, mon.cfg, mon, now=1000.0)
    fams = parse_openmetrics(text)
    names = {lab["alert"] for _, lab, _ in
             fams["kbz_alert_active"]["samples"]}
    assert names == {name for name, _ in ALERT_RULES}
