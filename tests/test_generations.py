"""--generations device-resident loop (ops/generations.py): the TPU
runs G full mutate -> execute -> triage -> reseed generations per host
dispatch and the host only drains the bounded findings ring + the
admission ledger.

Pins the ISSUE 9 contracts:
  * device/host novelty parity — the device-resident virgin-map
    update is bit-exact with the numpy reference ``_np_has_new_bits``
    (including the 0xFF new-tuple vs new-count 1/2 distinction and
    the crash/tmout simplify_trace maps) across random trace batches;
  * determinism/replay — a --generations campaign and the host-driven
    loop given the same RNG seed produce the same findings on the toy
    targets, and a SIGKILL mid-dispatch + --resume converges to the
    fault-free control (the PR 8 chaos harness);
  * the deterministic seed-slot policy is host-replayable
    (np_select_slot == _select_slot), admissions replay into real
    corpus arms with no duplicates, findings-ring overflow is COUNTED
    (never silent), and the watchdog deadline scales with the
    effective generation count (no false-positive exit 86).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_HANG, FUZZ_NONE, MAP_SIZE
from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.fuzzer.loop import Fuzzer
from killerbeez_tpu.instrumentation.afl import (
    _np_classify, _np_has_new_bits,
)
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.instrumentation.jit_harness import _triage_exact
from killerbeez_tpu.mutators.factory import mutator_factory
from killerbeez_tpu.ops.coverage import classify_counts, simplify_trace
from killerbeez_tpu.ops.generations import _select_slot, np_select_slot
from killerbeez_tpu.resilience.watchdog import DispatchWatchdog

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# device/host novelty parity (satellite: bit-exact with _np_has_new_bits)
# ---------------------------------------------------------------------------


def _np_simplify(trace: np.ndarray) -> np.ndarray:
    return np.where(trace == 0, np.uint8(1), np.uint8(128))


def _random_traces(rng, b):
    """Sparse random hit-count maps (the real shape of AFL traces)
    plus a couple of dense lanes and one all-zero lane."""
    traces = np.zeros((b, MAP_SIZE), np.uint8)
    for i in range(b - 1):
        k = int(rng.integers(1, 300))
        idx = rng.integers(0, MAP_SIZE, size=k)
        traces[i, idx] = rng.integers(1, 256, size=k).astype(np.uint8)
    traces[b - 2] = rng.integers(0, 256, size=MAP_SIZE)  # dense
    return traces  # lane b-1 stays all-zero


@pytest.mark.parametrize("case_seed", [0, 7, 91])
def test_virgin_update_bit_exact_with_np_reference(case_seed):
    """Property test: the exact-parity triage scan the generation
    loop threads its virgin maps through must agree byte-for-byte
    with the numpy single-exec reference — the 1-vs-2 ret distinction
    (new count bucket vs brand-new tuple, virgin byte still 0xFF),
    and the crash/tmout maps updated through simplify_trace only on
    the matching status."""
    rng = np.random.default_rng(case_seed)
    b = 24
    traces = _random_traces(rng, b)
    statuses = rng.choice(
        [FUZZ_NONE, FUZZ_CRASH, FUZZ_HANG], size=b).astype(np.int32)
    # start from PARTIALLY-seen maps so ret==1 (new bucket on a
    # non-0xFF byte) actually occurs: pre-fold a few traces in
    vb = np.full(MAP_SIZE, 0xFF, np.uint8)
    vc = np.full(MAP_SIZE, 0xFF, np.uint8)
    vh = np.full(MAP_SIZE, 0xFF, np.uint8)
    for t in _random_traces(rng, 4):
        vb &= ~_np_classify(t)
        vc &= ~_np_simplify(t)
    # some lanes repeat an earlier lane's trace: ret must be 0 there
    traces[5] = traces[1]
    traces[11] = traces[2]

    hvb, hvc, hvh = vb.copy(), vc.copy(), vh.copy()
    exp_ret = np.zeros(b, np.int32)
    exp_uc = np.zeros(b, bool)
    exp_uh = np.zeros(b, bool)
    for i in range(b):
        cls = _np_classify(traces[i])
        simp = _np_simplify(traces[i])
        exp_ret[i], hvb = _np_has_new_bits(hvb, cls)
        if statuses[i] == FUZZ_CRASH:
            r, hvc = _np_has_new_bits(hvc, simp)
            exp_uc[i] = r > 0
        elif statuses[i] == FUZZ_HANG:
            r, hvh = _np_has_new_bits(hvh, simp)
            exp_uh[i] = r > 0

    cls_d = classify_counts(jnp.asarray(traces))
    simp_d = simplify_trace(jnp.asarray(traces))
    new_paths, uc, uh, dvb, dvc, dvh = _triage_exact(
        jnp.asarray(vb), jnp.asarray(vc), jnp.asarray(vh),
        cls_d, simp_d, jnp.asarray(statuses))
    assert np.array_equal(np.asarray(new_paths), exp_ret)
    assert np.array_equal(np.asarray(uc), exp_uc)
    assert np.array_equal(np.asarray(uh), exp_uh)
    assert np.array_equal(np.asarray(dvb), hvb)
    assert np.array_equal(np.asarray(dvc), hvc)
    assert np.array_equal(np.asarray(dvh), hvh)
    # the distinction must actually have been exercised
    assert (exp_ret == 2).any() and (exp_ret == 1).any() \
        and (exp_ret == 0).any()


def test_select_slot_host_replay_parity():
    """The deterministic seed-slot policy: the device pick and the
    host replay (np_select_slot) agree for random ring occupancies —
    and always land on a FILLED slot."""
    rng = np.random.default_rng(5)
    for _ in range(64):
        s = int(rng.integers(2, 48))
        filled = np.zeros(s, np.int32)
        filled[0] = 1  # slot 0 pins the base seed
        filled[rng.integers(0, s, size=int(rng.integers(0, s)))] = 1
        gen_id = int(rng.integers(0, 2**32))
        salt = int(rng.integers(0, 2**32))
        dev = int(_select_slot(jnp.asarray(filled),
                               jnp.uint32(gen_id), jnp.uint32(salt)))
        host = np_select_slot(filled, gen_id, salt)
        assert dev == host
        assert filled[host] == 1


# ---------------------------------------------------------------------------
# determinism: generations campaign == host-driven loop
# ---------------------------------------------------------------------------

SEED = b"ABC@"


def _campaign(tmp_path, name, generations, *, target="test",
              seed=SEED, batch=64, n=1024, feedback=0, iopts=None,
              mopts='{"seed": 7}'):
    instr = instrumentation_factory(
        "jit_harness", iopts or json.dumps({"target": target}))
    mut = mutator_factory("havoc", mopts, seed)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / name), batch_size=batch,
                feedback=feedback, generations=generations,
                corpus_dir=(str(tmp_path / name / "corpus")
                            if feedback else None))
    fz.run(n)
    return fz, instr


def _findings(root):
    out = {}
    for kind in ("crashes", "hangs", "new_paths"):
        d = os.path.join(root, kind)
        out[kind] = sorted(
            f for f in (os.listdir(d) if os.path.isdir(d) else [])
            if len(f) == 32)
    return out


def test_generations_campaign_matches_host_loop(tmp_path):
    """THE determinism contract: with reseeding off (-fb 0) the
    device generation loop consumes the exact candidate stream the
    host-driven loop would (fold_in(base_key, absolute_iteration)),
    so findings AND final virgin maps are identical."""
    fh, ih = _campaign(tmp_path, "host", 0)
    fg, ig = _campaign(tmp_path, "gen", 4)
    assert fg.stats.iterations == fh.stats.iterations == 1024
    assert _findings(str(tmp_path / "gen")) == \
        _findings(str(tmp_path / "host"))
    assert fg.stats.crashes == fh.stats.crashes
    assert fg.stats.new_paths == fh.stats.new_paths
    for a, b in ((ig.virgin_bits, ih.virgin_bits),
                 (ig.virgin_crash, ih.virgin_crash),
                 (ig.virgin_tmout, ih.virgin_tmout)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # the comparison is about something: both loops found paths
    assert fg.stats.new_paths >= 1


def test_generations_partial_last_dispatch(tmp_path):
    """-n not divisible by G x batch: the loop clamps the effective
    generation count so the exec total is exact."""
    fg, _ = _campaign(tmp_path, "gen", 8, n=640, batch=64)
    assert fg.stats.iterations == 640


def test_generations_ring_admissions_replay_into_arms(tmp_path):
    """Feedback ON: the device's ring admissions replay through the
    host admission stage — real corpus arms (no duplicates), a
    ring_admit event per admission, and the scheduler keeps working.
    cgc_like under havoc admits within a few generations."""
    seed = b"CG\x02\x04\x05\x41xx"
    fz, _ = _campaign(
        tmp_path, "fb", 4, target="cgc_like", seed=seed,
        batch=256, n=4096, feedback=8, mopts='{"seed": 11}')
    md5s = [getattr(a, "md5", None) for a in fz.scheduler.arms]
    assert len(md5s) == len(set(md5s))          # no duplicate arms
    evs = [json.loads(l) for l in
           open(tmp_path / "fb" / "events.jsonl") if l.strip()]
    admits = [e for e in evs if e["type"] == "ring_admit"]
    assert admits, "device ring never admitted on cgc_like"
    store_dir = tmp_path / "fb" / "corpus"
    for e in admits:
        # every replayed admission is a real store entry
        assert (store_dir / e["md5"]).exists()
        assert e["slot"] >= 1                   # slot 0 stays pinned
    assert fz.stats.new_paths > 0


def test_generations_fb0_store_write_through_matches_host(tmp_path):
    """REGRESSION: -fb 0 with a corpus store configured.  The
    host-driven loop write-throughs every edge-novel find; with
    reseeding off the device ledger is empty, so the generations
    drain must admit ring lanes host-side — otherwise the store
    (and fleet sync) silently miss every find of the exact config
    the determinism contract pins."""
    def run(name, generations):
        instr = instrumentation_factory(
            "jit_harness", '{"target": "test"}')
        mut = mutator_factory("havoc", '{"seed": 7}', SEED)
        drv = driver_factory("file", None, instr, mut)
        fz = Fuzzer(drv, output_dir=str(tmp_path / name),
                    batch_size=64, feedback=0, generations=generations,
                    corpus_dir=str(tmp_path / name / "corpus"))
        fz.run(1024)
        return fz

    run("host", 0)
    run("gen", 4)

    def entries(name):
        d = tmp_path / name / "corpus"
        return sorted(f for f in os.listdir(d) if len(f) == 32)

    assert entries("gen") == entries("host")
    assert entries("gen"), "store stayed empty — nothing compared"


def test_findings_ring_overflow_counted_never_silent(tmp_path):
    """gen_findings_cap=2 on a findings-heavy target: the ring MUST
    overflow, and every dropped lane lands in the
    findings_ring_drops counter (no-silent-caps rule)."""
    fz, _ = _campaign(
        tmp_path, "ovf", 4,
        iopts='{"target": "test", "gen_findings_cap": 2}',
        batch=64, n=512)
    reg = fz.telemetry.registry
    drops = reg.counters.get("findings_ring_drops", 0)
    assert drops > 0


def test_generations_stands_down_with_crack_stage(tmp_path):
    """The crack stage injects host-side candidates + focus masks:
    --generations must stand down to the host-driven loop (same
    discipline as the superbatch path) and still complete."""
    instr = instrumentation_factory(
        "jit_harness", '{"target": "test"}')
    mut = mutator_factory("havoc", '{"seed": 7}', SEED)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=64,
                feedback=0, generations=4)
    class _StubCracker:                 # any non-None stands down
        def maybe_crack(self, fz):
            return None

    fz.cracker = _StubCracker()
    fz.run(256)
    assert fz._gen_warned
    assert fz.stats.iterations == 256


def test_supports_generations_gates(tmp_path):
    """supports_batch_generations: false for focus masks and edges
    mode — the device loop can't honor either."""
    instr = instrumentation_factory("jit_harness",
                                    '{"target": "test"}')
    mut = mutator_factory("havoc", '{"seed": 7}', SEED)
    drv = driver_factory("file", None, instr, mut)
    assert drv.supports_batch_generations()
    mut.set_focus_mask([0, 1])
    assert not drv.supports_batch_generations()
    mut.set_focus_mask(None)
    assert drv.supports_batch_generations()
    instr2 = instrumentation_factory(
        "jit_harness", '{"target": "test", "edges": 1}')
    drv2 = driver_factory("file", None, instr2, mut)
    assert not drv2.supports_batch_generations()


# ---------------------------------------------------------------------------
# watchdog scaling (satellite: no false-positive exit 86 under -G)
# ---------------------------------------------------------------------------


def test_watchdog_deadline_scales_with_generation_count():
    wd = DispatchWatchdog(multiplier=4.0, min_deadline=0.05,
                          max_deadline=100.0)
    wd._ema_batch_s = 0.5                       # warm estimate
    base = wd.deadline()
    assert base == pytest.approx(2.0)
    wd.note_dispatch_scale(16)
    assert wd.deadline() == pytest.approx(16 * base)
    # the ceiling scales too: a large G is not clamped back to a
    # one-batch budget (which would false-positive by construction)
    wd2 = DispatchWatchdog(multiplier=4.0, min_deadline=0.05,
                           max_deadline=1.0)
    wd2._ema_batch_s = 0.5
    wd2.note_dispatch_scale(64)
    assert wd2.deadline() == pytest.approx(64.0)
    # cold start grants the (scaled) ceiling
    wd3 = DispatchWatchdog(min_deadline=0.05, max_deadline=2.0)
    wd3.note_dispatch_scale(8)
    assert wd3.deadline() == pytest.approx(16.0)


def test_watchdog_ema_stays_per_batch_across_scales():
    """Observed guarded waits fold into the EMA divided by the armed
    scale — a G-generation dispatch must not inflate the per-batch
    estimate G-fold (which would blunt the watchdog for the host
    loop after a mode switch)."""
    wd = DispatchWatchdog(multiplier=4.0, min_deadline=0.01,
                          max_deadline=100.0)
    wd.note_dispatch_scale(10)
    wd._arm("dispatch")
    time.sleep(0.2)                 # a "10-generation" wait
    wd._disarm()
    # EMA saw ~0.02s/batch, not ~0.2s
    assert 0.0 < wd._ema_batch_s < 0.1


def test_watchdog_no_false_positive_on_scaled_dispatch():
    """REGRESSION (satellite 1): a G-generation dispatch legitimately
    waits ~G x one batch.  Unscaled, this guard blows its deadline
    (monitor tick 0.25s); with note_dispatch_scale(G) it must not."""
    fired = threading.Event()
    wd = DispatchWatchdog(multiplier=2.0, min_deadline=0.1,
                          max_deadline=60.0,
                          action=fired.set)
    wd._ema_batch_s = 0.1           # warm: one batch ~ 0.1s
    assert wd.deadline() == pytest.approx(0.2)
    wd.note_dispatch_scale(8)       # dispatch now covers 8 batches
    try:
        with wd.guard("dispatch"):  # guard starts the monitor
            time.sleep(1.0)         # ~5x the UNSCALED deadline
        assert not fired.is_set()
    finally:
        wd.stop()
    assert wd.stalls == 0


class _RecordingWatchdog(DispatchWatchdog):
    """A real watchdog (huge deadlines — never fires) that records
    every note_dispatch_scale call the loop makes."""

    def __init__(self):
        super().__init__(multiplier=1e6, min_deadline=1e6,
                         max_deadline=1e6)
        self.scales = []

    def note_dispatch_scale(self, k):
        self.scales.append(int(k))
        super().note_dispatch_scale(k)


def test_watchdog_scale_follows_drained_dispatch(tmp_path):
    """REGRESSION: with a pipeline of pending dispatches, the drain
    waits on the OLDEST one — its guard must arm with THAT
    dispatch's generation count.  A shrunken tail dispatch (g_eff 1)
    queued behind a full-G one would otherwise clamp the full-G
    drain to a 1-batch deadline: false-positive exit 86."""
    wd = _RecordingWatchdog()
    instr = instrumentation_factory(
        "jit_harness", '{"target": "test"}')
    mut = mutator_factory("havoc", '{"seed": 7}', SEED)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=64,
                feedback=0, generations=4, watchdog=wd)
    try:
        fz.run(320)     # one g=4 dispatch + one g=1 tail dispatch
    finally:
        wd.stop()
    assert fz.stats.iterations == 320
    # dispatch A (g=4), dispatch B (g=1), drain A re-arms at A's
    # OWN scale 4 (the regression), drain B at 1, final reset to 1
    assert wd.scales == [4, 1, 4, 1, 1]


def test_generations_tail_quantizes_to_pow2(tmp_path):
    """Tail dispatches quantize the generation count down to a power
    of two: g is a STATIC jit argument, so an arbitrary tail G would
    recompile the whole generation scan for one dispatch.  The exec
    total must stay exact regardless."""
    wd = _RecordingWatchdog()
    instr = instrumentation_factory(
        "jit_harness", '{"target": "test"}')
    mut = mutator_factory("havoc", '{"seed": 7}', SEED)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=64,
                feedback=0, generations=8, watchdog=wd)
    try:
        fz.run(64 * 11)     # 8 + (3 -> 2) + 1 generations
    finally:
        wd.stop()
    assert fz.stats.iterations == 64 * 11
    assert all(k & (k - 1) == 0 for k in wd.scales), wd.scales
    assert wd.scales[:2] == [8, 2]


# ---------------------------------------------------------------------------
# kb-timeline generations report (satellite: occupancy artifact)
# ---------------------------------------------------------------------------


def test_timeline_generations_report_device_bound():
    from killerbeez_tpu.tools.timeline_tool import generations_report
    spans = [
        {"name": "in_flight", "t0": 0.0, "t1": 95.0,
         "args": {"generations": 16, "batch": 0}},
        {"name": "in_flight", "t0": 95.0, "t1": 200.0,
         "args": {"generations": 16, "batch": 1}},
        # host stages: a thin slice of the window
        {"name": "triage", "t0": 96.0, "t1": 102.0, "args": {}},
        {"name": "host_transfer", "t0": 95.0, "t1": 96.0, "args": {}},
        # a host stage OUTSIDE the generation window must not count
        {"name": "mutate", "t0": 300.0, "t1": 400.0, "args": {}},
    ]
    gr = generations_report(spans)
    assert gr["dispatches"] == 2
    assert gr["generations_total"] == 32
    assert gr["generations_min"] == gr["generations_max"] == 16
    assert gr["device_occupancy"] == pytest.approx(1.0)
    assert gr["host_occupancy"] == pytest.approx(7.0 / 200.0)
    assert gr["device_bound"] is True


def test_timeline_generations_report_absent_without_mode():
    from killerbeez_tpu.tools.timeline_tool import generations_report
    spans = [{"name": "in_flight", "t0": 0, "t1": 1,
              "args": {"batch": 0}}]
    assert generations_report(spans) is None


def test_trace_campaign_reports_device_bound(tmp_path):
    """Acceptance artifact: a --generations campaign with --trace
    yields a kb-timeline report whose critical path is the device
    stage (host occupancy below the dispatch window)."""
    from killerbeez_tpu.tools.timeline_tool import build_report
    instr = instrumentation_factory("jit_harness",
                                    '{"target": "cgc_like"}')
    mut = mutator_factory("havoc", '{"seed": 11}', b"CG\x02\x04\x05Axx")
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=256,
                feedback=0, generations=8, trace=65536)
    fz.run(8192)
    doc = json.load(open(tmp_path / "o" / "trace.json"))
    report = build_report(doc, None, None)
    gr = report.get("generations")
    assert gr and gr["dispatches"] >= 2
    assert gr["generations_max"] <= 8
    assert gr["device_bound"], (
        "host stages on the critical path: "
        f"device {gr['device_occupancy']:.1%} vs "
        f"host {gr['host_occupancy']:.1%}")


# ---------------------------------------------------------------------------
# CLI: chaos kill mid-dispatch + --resume converges (PR 8 harness)
# ---------------------------------------------------------------------------

CLI_SEED = b"\x00" * 8


def _cli_args(out, extra=()):
    return ["file", "jit_harness", "havoc",
            "-i", '{"target": "cgc_like"}',
            "-m", '{"seed": 11}', "-fb", "0",
            "-sf", "seed.bin", "-o", out, "-b", "256", "-n", "1024",
            "--corpus-dir", os.path.join(out, "corpus"), *extra]


def _run_cli(tmp_path, args, timeout=240):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO_ROOT +
                os.pathsep + env.get("PYTHONPATH", "")})
    (tmp_path / "seed.bin").write_bytes(CLI_SEED)
    return subprocess.run(
        [sys.executable, "-m", "killerbeez_tpu.fuzzer", *args],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=timeout)


def test_cli_generations_kill_mid_dispatch_resume_converges(tmp_path):
    """SIGKILL while draining a G-generation dispatch, then --resume:
    the campaign converges to the fault-free control's exact findings
    set, and the host-driven loop with the same RNG seed agrees too
    (the full ISSUE 9 determinism criterion, via the PR 8 chaos
    harness)."""
    r = _run_cli(tmp_path, _cli_args("ctl_host"))
    assert r.returncode == 0, r.stderr[-2000:]
    r = _run_cli(tmp_path, _cli_args("ctl_gen", ["-G", "4"]))
    assert r.returncode == 0, r.stderr[-2000:]
    control = _findings(str(tmp_path / "ctl_host"))
    assert any(control.values()), "control found nothing to compare"
    # device-resident == host-driven, same seed
    assert _findings(str(tmp_path / "ctl_gen")) == control

    spec = json.dumps({"faults": [
        {"point": "device_wait", "mode": "kill", "hit": 1}]})
    r = _run_cli(tmp_path,
                 _cli_args("out", ["-G", "4", "--chaos", spec]))
    assert r.returncode == -signal.SIGKILL
    r = _run_cli(tmp_path, _cli_args("out", ["-G", "4", "--resume"]))
    assert r.returncode == 0, r.stderr[-2000:]
    assert _findings(str(tmp_path / "out")) == control
    # monotone event seq across the kill/resume boundary
    seqs = [json.loads(l)["seq"]
            for l in open(tmp_path / "out" / "events.jsonl")
            if l.strip()]
    assert seqs and all(b > a for a, b in zip(seqs, seqs[1:]))


def test_cli_generations_stats_row_and_occupancy(tmp_path):
    """kb-stats renders the genloop row from a real campaign's
    stats snapshot (generations_per_dispatch + ring gauge)."""
    from killerbeez_tpu.tools.stats_tui import render
    r = _run_cli(tmp_path, _cli_args(
        "out", ["-G", "4", "--stats-interval", "0.1"]))
    assert r.returncode == 0, r.stderr[-2000:]
    tail = [json.loads(l) for l in
            open(tmp_path / "out" / "stats.jsonl") if l.strip()]
    snap = tail[-1]
    assert snap["gauges"].get("generations_per_dispatch") == 4
    text = render(snap)
    assert "generations/dispatch (device-resident)" in text
