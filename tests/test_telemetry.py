"""Telemetry subsystem tests — registry math (EMA decay, histogram
buckets), merge algebra (associative + commutative under random
snapshots), atomic fuzzer_stats writes (a reader never sees a torn
file), sink file formats, worker heartbeat retry/backoff, the
kb-stats renderer, and the flight recorder (span ring + Chrome
export, event log schema/seq contract, kb-timeline analysis, the
manager /api/events exchange)."""

import json
import os
import random
import threading

import pytest

from killerbeez_tpu.telemetry import (
    EventLog, MetricsRegistry, StageTimer, Telemetry, TraceRecorder,
    last_event_seq, merge, merge_events, merge_two,
    parse_fuzzer_stats, read_events,
)
from killerbeez_tpu.telemetry.metrics import (
    EmaRate, HIST_BUCKETS, Histogram,
)
from killerbeez_tpu.telemetry.sink import (
    PLOT_FIELDS, StatsSink, plot_row, write_fuzzer_stats,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- EMA rate ----------------------------------------------------------


def test_ema_rate_converges_to_steady_rate():
    clk = FakeClock()
    r = EmaRate(tau=10.0, time_fn=clk)
    r.add(0)                             # anchor t0
    for _ in range(200):                 # 100/s steady stream
        clk.advance(1.0)
        r.add(100)
    assert r.rate == pytest.approx(100.0, rel=0.01)
    assert 0.99 < r.weight <= 1.0


def test_ema_rate_decays_toward_recent_rate():
    clk = FakeClock()
    r = EmaRate(tau=5.0, time_fn=clk)
    r.add(0)
    for _ in range(50):
        clk.advance(1.0)
        r.add(1000)                      # fast phase: 1000/s
    fast = r.rate
    for _ in range(50):
        clk.advance(1.0)
        r.add(10)                        # slow phase: 10/s
    assert fast == pytest.approx(1000.0, rel=0.05)
    assert r.rate == pytest.approx(10.0, rel=0.05)  # forgot the past


def test_ema_rate_first_sample_only_anchors():
    clk = FakeClock()
    r = EmaRate(time_fn=clk)
    r.add(500)
    assert r.rate == 0.0 and r.weight == 0.0


# -- histogram ---------------------------------------------------------


def test_histogram_bucket_edges_inclusive():
    h = Histogram()
    h.observe(HIST_BUCKETS[0])           # == first edge -> bucket 0
    h.observe(HIST_BUCKETS[0] * 1.001)   # just above -> bucket 1
    h.observe(HIST_BUCKETS[-1] * 2)      # beyond all edges -> overflow
    assert h.counts[0] == 1
    assert h.counts[1] == 1
    assert h.counts[-1] == 1
    assert h.total == 3
    assert h.sum == pytest.approx(
        HIST_BUCKETS[0] * 2.001 + HIST_BUCKETS[-1] * 2)


def test_histogram_matches_linear_scan():
    rng = random.Random(7)
    h = Histogram()
    vals = [rng.uniform(0, 1e-1) for _ in range(500)]
    for v in vals:
        h.observe(v)
    for i, edge in enumerate(HIST_BUCKETS):
        lo = HIST_BUCKETS[i - 1] if i else float("-inf")
        want = sum(1 for v in vals if lo < v <= edge)
        assert h.counts[i] == want, f"bucket {i}"


def test_histogram_percentile_estimates():
    """p50/p90/p99 from bucket interpolation: every estimate lands
    within its observation's bucket, as_dict carries the keys, and
    percentiles_from_counts (the merge path) agrees."""
    from killerbeez_tpu.telemetry.metrics import (
        HIST_BUCKETS, percentiles_from_counts,
    )
    h = Histogram()
    for _ in range(90):
        h.observe(1e-4)                  # bucket around 1e-4
    for _ in range(10):
        h.observe(0.5)                   # slow tail
    d = h.as_dict()
    assert set(d) >= {"p50", "p90", "p99"}
    # p50/p90 in the fast bucket, p99 in the slow one
    assert 6.4e-05 < d["p50"] <= 1.28e-4
    assert 6.4e-05 < d["p90"] <= 1.28e-4
    assert 0.25 < d["p99"] <= 0.524288
    assert d["p50"] <= d["p90"] <= d["p99"]
    assert h.percentile(0.5) == d["p50"]
    assert percentiles_from_counts(h.counts) == {
        "p50": d["p50"], "p90": d["p90"], "p99": d["p99"]}
    # overflow-bucket observations clamp to the last finite edge
    h2 = Histogram()
    h2.observe(1e9)
    assert h2.as_dict()["p99"] == HIST_BUCKETS[-1]
    # empty histogram: no percentile keys, percentile() is 0
    assert "p50" not in Histogram().as_dict()
    assert Histogram().percentile(0.5) == 0.0
    # merged hists re-derive from merged counts (aggregate path)
    m = merge_two({"hists": {"x": h.as_dict()}},
                  {"hists": {"x": h.as_dict()}})
    assert m["hists"]["x"]["total"] == 200
    assert m["hists"]["x"]["p50"] == d["p50"]  # same distribution


# -- registry + stage timer -------------------------------------------


def test_registry_counters_and_run_windows():
    clk = FakeClock()
    reg = MetricsRegistry(time_fn=clk)
    reg.count("execs", 100)
    reg.count("execs", 28)
    clk.advance(100.0)                   # idle gap: not active time
    reg.run_started()
    clk.advance(4.0)
    reg.run_ended()
    assert reg.counters["execs"] == 128
    assert reg.active_seconds() == pytest.approx(4.0)
    assert reg.execs_per_sec() == pytest.approx(32.0)  # active, not age
    assert reg.elapsed() == pytest.approx(104.0)


def test_stage_timer_records_histogram_and_total():
    reg = MetricsRegistry()
    t = StageTimer(reg)
    with t("triage"):
        pass
    with t("triage"):
        with t("fs_write"):              # spans nest
            pass
    assert reg.hists["triage"].total == 2
    assert reg.hists["fs_write"].total == 1
    assert reg.counters["triage_seconds"] >= 0
    split = reg.stage_split()
    assert set(split) <= {"triage", "fs_write"}
    assert sum(split.values()) == pytest.approx(1.0)


def test_snapshot_shape_round_trips_json():
    reg = MetricsRegistry()
    reg.count("execs", 5)
    reg.gauge("corpus_size", 3)
    reg.rate("execs", 5)
    reg.observe("execute", 0.01)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["counters"]["execs"] == 5
    assert snap["gauges"]["corpus_size"] == 3
    assert "execs" in snap["rates"]
    assert "execute" in snap["hists"]
    assert "execs_per_sec" in snap["derived"]


# -- merge algebra -----------------------------------------------------


def _rand_snapshot(rng):
    names = ["execs", "crashes", "new_paths", "hangs"]
    return {
        "t": rng.uniform(1000, 2000),
        "start_time": rng.uniform(0, 1000),
        "counters": {n: rng.randrange(0, 10000)
                     for n in rng.sample(names, rng.randrange(1, 4))},
        "gauges": {n: rng.uniform(0, 50)
                   for n in rng.sample(["corpus_size", "depth"],
                                       rng.randrange(0, 3))},
        "rates": {n: {"rate": rng.uniform(0, 1e6),
                      "weight": rng.uniform(0, 1)}
                  for n in rng.sample(names, rng.randrange(0, 3))},
        "hists": {n: {"counts": [rng.randrange(0, 9)
                                 for _ in range(4)],
                      "total": rng.randrange(0, 30),
                      "sum": rng.uniform(0, 5)}
                  for n in rng.sample(["execute", "triage"],
                                      rng.randrange(0, 3))},
        # fleet health fields ride snapshots too (the /api/fleet
        # merged view) and must fold associatively like the rest
        "health": {w: {"status": rng.choice(["healthy", "stale",
                                             "dead"]),
                       "first_seen": rng.uniform(0, 100),
                       "last_seen": rng.uniform(100, 200)}
                   for w in rng.sample(["w1", "w2", "w3"],
                                       rng.randrange(0, 3))},
    }


def _assert_snap_equal(a, b):
    assert a["counters"] == pytest.approx(b["counters"])
    assert a["gauges"] == pytest.approx(b["gauges"])
    assert set(a["rates"]) == set(b["rates"])
    for k in a["rates"]:
        assert a["rates"][k]["rate"] == \
            pytest.approx(b["rates"][k]["rate"])
        assert a["rates"][k]["weight"] == \
            pytest.approx(b["rates"][k]["weight"])
    assert set(a["hists"]) == set(b["hists"])
    for k in a["hists"]:
        assert a["hists"][k]["counts"] == b["hists"][k]["counts"]
        assert a["hists"][k]["total"] == b["hists"][k]["total"]
    assert a.get("t") == pytest.approx(b.get("t"))
    assert a.get("start_time") == pytest.approx(b.get("start_time"))
    assert a.get("health", {}) == b.get("health", {})


def test_merge_is_associative_and_commutative():
    rng = random.Random(0xbee5)
    for _ in range(40):                  # property check over randoms
        a, b, c = (_rand_snapshot(rng) for _ in range(3))
        _assert_snap_equal(merge_two(a, b), merge_two(b, a))
        _assert_snap_equal(merge_two(merge_two(a, b), c),
                           merge_two(a, merge_two(b, c)))
        _assert_snap_equal(merge([a, b, c]), merge([c, b, a]))


def test_shard_stat_snapshots_fold():
    """The mesh campaign's per-epoch fold: dp shards' snapshots merge
    into the fleet view (execs sum across shards, step clock max's)."""
    from killerbeez_tpu.parallel.distributed import (
        shard_stat_snapshots,
    )

    class FakeMesh:
        shape = {"dp": 4, "mp": 2}

    snaps = shard_stat_snapshots(FakeMesh(), 16, 3)
    assert len(snaps) == 4               # one per dp shard
    m = merge(snaps)
    assert m["counters"]["execs"] == 64  # 4 shards x 16 lanes
    assert m["gauges"]["shard_step"] == 3
    assert m["gauges"]["lanes_per_shard"] == 16
    # epoch folds accumulate associatively into the campaign total
    acc = merge_two(m, merge(shard_stat_snapshots(FakeMesh(), 16, 4)))
    assert acc["counters"]["execs"] == 128
    assert acc["gauges"]["shard_step"] == 4


def test_merge_semantics():
    a = {"counters": {"execs": 100, "crashes": 1},
         "gauges": {"corpus_size": 5},
         "rates": {"execs": {"rate": 1000.0, "weight": 1.0}}}
    b = {"counters": {"execs": 50},
         "gauges": {"corpus_size": 9},
         "rates": {"execs": {"rate": 400.0, "weight": 0.5}}}
    m = merge([a, b])
    assert m["counters"]["execs"] == 150         # summed
    assert m["counters"]["crashes"] == 1
    assert m["gauges"]["corpus_size"] == 9       # max
    # weight-weighted mean: (1000*1 + 400*0.5) / 1.5
    assert m["rates"]["execs"]["rate"] == pytest.approx(800.0)
    assert m["rates"]["execs"]["weight"] == pytest.approx(1.5)
    assert merge([]) is None


def test_merge_health_semantics():
    """Per worker, the newest last_seen supplies the status (tie:
    worse status wins), first_seen min's, last_seen max's."""
    from killerbeez_tpu.telemetry import merge_health
    a = {"health": {
        "w1": {"status": "healthy", "first_seen": 10.0,
               "last_seen": 100.0},
        "w2": {"status": "dead", "first_seen": 5.0,
               "last_seen": 50.0}}}
    b = {"health": {
        "w1": {"status": "stale", "first_seen": 20.0,
               "last_seen": 90.0},      # older: loses the status
        "w3": {"status": "healthy", "first_seen": 1.0,
               "last_seen": 60.0}}}
    m = merge_two(a, b)["health"]
    assert m["w1"]["status"] == "healthy"     # newest record wins
    assert m["w1"]["first_seen"] == 10.0      # field-wise min
    assert m["w1"]["last_seen"] == 100.0      # field-wise max
    assert m["w2"]["status"] == "dead"        # one-sided copies
    assert m["w3"]["status"] == "healthy"
    # same last_seen: the worse status wins (dead > healthy)
    t = {"status": "healthy", "last_seen": 10.0}
    d = {"status": "dead", "last_seen": 10.0}
    assert merge_health({"w": t}, {"w": d})["w"]["status"] == "dead"
    assert merge_health({"w": d}, {"w": t})["w"]["status"] == "dead"


# -- sink: atomicity + formats ----------------------------------------


def _snap(execs, paths=0, t=1000.0):
    return {"t": t, "start_time": 0.0, "elapsed": t,
            "counters": {"execs": execs, "new_paths": paths},
            "gauges": {}, "rates": {},
            "derived": {"execs_per_sec": execs / t,
                        "execs_per_sec_ema": 0.0}}


def test_fuzzer_stats_write_is_atomic_under_reader(tmp_path):
    """A tailer hammering the file during 200 rewrites must always
    parse a COMPLETE snapshot — os.replace publishes whole files
    only (the torn-write guarantee external dashboards rely on)."""
    path = str(tmp_path / "fuzzer_stats")
    write_fuzzer_stats(path, _snap(0))
    keys = set(parse_fuzzer_stats(path))
    stop = threading.Event()
    torn = []

    def reader():
        while not stop.is_set():
            got = parse_fuzzer_stats(path)
            if set(got) != keys or not all(v for v in got.values()):
                torn.append(got)

    th = threading.Thread(target=reader)
    th.start()
    try:
        for i in range(1, 201):
            write_fuzzer_stats(path, _snap(i * 1000, paths=i))
    finally:
        stop.set()
        th.join()
    assert not torn, torn[:3]
    assert not os.path.exists(path + ".tmp")  # tmp never left behind
    assert parse_fuzzer_stats(path)["execs_done"] == "200000"


def test_failed_write_leaves_previous_stats_intact(tmp_path,
                                                   monkeypatch):
    path = str(tmp_path / "fuzzer_stats")
    write_fuzzer_stats(path, _snap(42))
    real_replace = os.replace

    def boom(src, dst):
        raise OSError("disk full")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        write_fuzzer_stats(path, _snap(999))
    monkeypatch.setattr(os, "replace", real_replace)
    assert parse_fuzzer_stats(path)["execs_done"] == "42"


def test_sink_files_and_plot_monotone(tmp_path):
    clk = FakeClock()
    reg = MetricsRegistry(time_fn=clk)
    sink = StatsSink(str(tmp_path), reg, interval_s=10.0)
    for i in range(5):
        reg.count("execs", 100)
        reg.count("new_paths", 2)
        clk.advance(11.0)
        assert sink.maybe_flush()
        assert not sink.maybe_flush()    # within the interval: no-op
    rows = [r for r in
            (tmp_path / "plot_data").read_text().splitlines()
            if not r.startswith("#")]
    assert len(rows) == 5
    execs = [int(r.split(",")[1]) for r in rows]
    assert execs == sorted(execs)        # monotone cumulative
    assert execs[-1] == 500
    jl = [json.loads(l) for l in
          (tmp_path / "stats.jsonl").read_text().splitlines()]
    assert len(jl) == 5
    assert jl[-1]["counters"]["execs"] == 500
    stats = parse_fuzzer_stats(str(tmp_path / "fuzzer_stats"))
    assert stats["execs_done"] == "500"
    assert stats["paths_total"] == "10"
    assert len(plot_row(_snap(1)).split(", ")) == len(PLOT_FIELDS)


# -- worker heartbeat retry -------------------------------------------


def test_request_retry_backs_off_then_succeeds(monkeypatch):
    from killerbeez_tpu.manager import worker as w
    calls = {"n": 0}
    sleeps = []

    def flaky(url, payload=None, method="POST"):
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("refused")
        return {"ok": True}

    monkeypatch.setattr(w, "_request", flaky)
    monkeypatch.setattr(w.time, "sleep", sleeps.append)
    assert w._request_retry("http://x/api", {}) == {"ok": True}
    assert calls["n"] == 3
    assert sleeps == [0.5, 1.0]          # exponential backoff


def test_request_retry_exhausts_and_raises(monkeypatch):
    from killerbeez_tpu.manager import worker as w

    def down(url, payload=None, method="POST"):
        raise ConnectionError("refused")

    monkeypatch.setattr(w, "_request", down)
    monkeypatch.setattr(w.time, "sleep", lambda s: None)
    with pytest.raises(ConnectionError):
        w._request_retry("http://x/api", {}, attempts=4)


def test_heartbeat_reads_latest_snapshot(tmp_path, monkeypatch):
    from killerbeez_tpu.manager import worker as w
    out = tmp_path / "output"
    out.mkdir()
    assert w.read_latest_snapshot(str(out)) is None   # no file yet
    with open(out / "stats.jsonl", "w") as f:
        f.write(json.dumps(_snap(100)) + "\n")
        f.write(json.dumps(_snap(900)) + "\n")
    assert w.read_latest_snapshot(str(out))["counters"]["execs"] == 900
    posts = []
    monkeypatch.setattr(
        w, "_request_retry",
        lambda url, payload=None, **kw: posts.append((url, payload)))
    hb = w.Heartbeat("http://mgr", "7", "w1", str(out), interval=99)
    assert hb.beat()
    (url, payload), = posts
    assert url == "http://mgr/api/stats/7"
    assert payload["worker"] == "w1"
    assert payload["snapshot"]["counters"]["execs"] == 900
    # a record torn mid-append falls back to the previous complete
    # one (the final heartbeat must never be dropped over a tail race)
    with open(out / "stats.jsonl", "a") as f:
        f.write(json.dumps(_snap(950))[:40])          # no newline, torn
    assert w.read_latest_snapshot(str(out))["counters"]["execs"] == 900
    # O(1) tail: only the last window bytes are read on a long stream
    with open(out / "stats.jsonl", "a") as f:
        f.write("\n")
        for i in range(2000):
            f.write(json.dumps(_snap(i)) + "\n")
    assert w.read_latest_snapshot(
        str(out), window=4096)["counters"]["execs"] == 1999


def test_heartbeat_survives_dead_manager(tmp_path, monkeypatch):
    from killerbeez_tpu.manager import worker as w
    out = tmp_path / "o"
    out.mkdir()
    (out / "stats.jsonl").write_text(json.dumps(_snap(1)) + "\n")

    def down(url, payload=None, **kw):
        raise ConnectionError("refused")

    monkeypatch.setattr(w, "_request_retry", down)
    hb = w.Heartbeat("http://gone", "1", "w", str(out), interval=99)
    assert hb.beat() is False            # warns, never raises


# -- kb-stats renderer -------------------------------------------------


def test_stats_tui_render_and_once(tmp_path, capsys):
    from killerbeez_tpu.tools import stats_tui
    snap = _snap(1_500_000, paths=42, t=3700.0)
    snap["counters"].update(crashes=3, unique_crashes=2,
                            execute_seconds=8.0, triage_seconds=2.0)
    snap["gauges"] = {"corpus_size": 42, "pipeline_depth": 24}
    frame = stats_tui.render(snap)
    assert "1.50M" in frame              # execs humanized
    assert "01:01:40" in frame           # 3700s
    assert "crashes" in frame and "(2 unique)" in frame
    assert "stage split" in frame
    assert "execute" in frame and "80.0%" in frame
    # --once against a real stats.jsonl
    (tmp_path / "stats.jsonl").write_text(json.dumps(snap) + "\n")
    assert stats_tui.main([str(tmp_path), "--once"]) == 0
    assert "1.50M" in capsys.readouterr().out
    # missing file: clean nonzero exit, no traceback
    assert stats_tui.main([str(tmp_path / "nope"), "--once"]) == 1


def test_stats_tui_reads_manager_merge(tmp_path):
    from killerbeez_tpu.manager import ManagerServer
    from killerbeez_tpu.tools.stats_tui import read_manager
    s = ManagerServer(port=0)
    s.start()
    try:
        s.db.upsert_campaign_stats("c1", "w1", _snap(100))
        s.db.upsert_campaign_stats("c1", "w2", _snap(50))
        merged = read_manager(f"http://127.0.0.1:{s.port}", "c1")
    finally:
        s.stop()
    assert merged["counters"]["execs"] == 150
    assert merged["_n_workers"] == 2


# -- flight recorder: span ring ----------------------------------------


def _balance_check(doc):
    """Every tid's B/E stream must stay balanced and end at zero;
    every async b must have exactly one matching e (by tid+name+id)."""
    depth = {}
    a_open = set()
    for ev in doc["traceEvents"]:
        tid = ev["tid"]
        if ev["ph"] == "B":
            depth[tid] = depth.get(tid, 0) + 1
        elif ev["ph"] == "E":
            depth[tid] = depth.get(tid, 0) - 1
            assert depth[tid] >= 0, f"E without B on tid {tid}"
        elif ev["ph"] == "b":
            key = (tid, ev["name"], ev["id"])
            assert key not in a_open, f"double async begin {key}"
            a_open.add(key)
        elif ev["ph"] == "e":
            key = (tid, ev["name"], ev["id"])
            assert key in a_open, f"e without b {key}"
            a_open.remove(key)
    assert all(v == 0 for v in depth.values()), depth
    assert not a_open, a_open


def test_trace_recorder_balanced_export_mid_span(tmp_path):
    """Chrome export stays balanced under a forced mid-span shutdown:
    open spans get synthetic closes, the JSON loads, timestamps are
    relative microseconds."""
    tr = TraceRecorder(max_events=256)
    tr.begin("execute", args={"batch": 0})
    tr.end("execute")
    tr.begin("triage")
    tr.begin("fs_write")                 # nested, BOTH left open:
    doc = tr.to_chrome()                 # the mid-span "shutdown"
    _balance_check(doc)
    names = [e["name"] for e in doc["traceEvents"]
             if e["ph"] in "BE"]
    assert names.count("triage") == 2 and names.count("fs_write") == 2
    # atomic file export round-trips
    p = str(tmp_path / "trace.json")
    assert tr.export(p)
    assert not os.path.exists(p + ".tmp")
    doc2 = json.load(open(p))
    _balance_check(doc2)
    assert doc2["otherData"]["wall_t0"] > 0


def test_trace_recorder_ring_wrap_drops_orphan_ends():
    """When the ring overwrites old events, an E whose B wrapped away
    must be dropped — the export is still balanced."""
    tr = TraceRecorder(max_events=8)
    for i in range(50):                  # 100 events through an
        tr.begin("execute")              # 8-slot ring
        tr.end("execute")
    tr.begin("triage")                   # guarantee a B survives
    doc = tr.to_chrome()
    _balance_check(doc)
    assert tr.dropped == 50 * 2 + 1 - 8
    assert doc["otherData"]["events_dropped"] == tr.dropped


def test_trace_recorder_lanes_and_span_cm():
    tr = TraceRecorder()
    tr.lane = 3
    tr.name_lane(3, "batch-03")
    tr.begin("execute")
    tr.end("execute")
    with tr.span("crack", lane="crack", args={"edges": 2}):
        tr.instant("plateau")
    assert tr.lane == 3                  # span() restored the lane
    doc = tr.to_chrome()
    _balance_check(doc)
    crack_tid = tr.lane_id("crack")
    by_tid = {}
    for ev in doc["traceEvents"]:
        by_tid.setdefault(ev["tid"], []).append(ev)
    assert any(e["ph"] == "B" and e["name"] == "crack"
               for e in by_tid[crack_tid])
    assert any(e["ph"] == "i" and e["name"] == "plateau"
               for e in by_tid[crack_tid])
    # thread_name metadata labels both lanes
    meta = {e["tid"]: e["args"]["name"]
            for e in doc["traceEvents"] if e["ph"] == "M"}
    assert meta[3] == "batch-03" and meta[crack_tid] == "crack"


def test_stage_timer_feeds_both_registry_and_tracer():
    reg = MetricsRegistry()
    tr = TraceRecorder()
    t = StageTimer(reg, tr)
    with t("triage"):
        with t("fs_write"):
            pass
    assert reg.hists["triage"].total == 1
    doc = tr.to_chrome()
    _balance_check(doc)
    assert [e["name"] for e in doc["traceEvents"]
            if e["ph"] == "B"] == ["triage", "fs_write"]
    # a span pins its lane at entry: retargeting the recorder's
    # current lane mid-span (the loop triages other batches inside
    # corpus_feedback spans) must not split the B/E pair across lanes
    tr.lane = 1
    with t("corpus_feedback"):
        tr.lane = 2
        tr.begin("in_flight")
        tr.end("in_flight")
    doc = tr.to_chrome()
    _balance_check(doc)
    cf = [e for e in doc["traceEvents"]
          if e["name"] == "corpus_feedback"]
    assert [e["tid"] for e in cf] == [1, 1]


def test_async_in_flight_does_not_cross_sync_spans():
    """The regression the async pair exists for: a batch's in-flight
    window closes while an unrelated sync span is open on the SAME
    lane (pipeline ramp-up + _drain_ready inside corpus_feedback).
    Stack-matched B/E would cross the pairs; async b/e must not."""
    from killerbeez_tpu.tools import timeline_tool as tt
    tr = TraceRecorder()
    tr.lane = 0
    tr.async_begin("in_flight", 0, args={"batch": 0})
    tr.begin("corpus_feedback")          # sync span opens...
    tr.async_end("in_flight", 0)         # ...in-flight closes inside
    tr.end("corpus_feedback")
    # mid-span shutdown with an open async pair stays balanced too
    tr.async_begin("in_flight", 1)
    doc = tr.to_chrome()
    _balance_check(doc)
    spans = tt.spans_from_chrome(doc)
    by = {s["name"]: s for s in spans}
    assert set(by) == {"in_flight", "corpus_feedback"}
    # each span got its OWN begin/end (no swapped durations):
    # in_flight opened first and closed before corpus_feedback did
    inf = [s for s in spans if s["name"] == "in_flight"
           and s["args"]]
    cf = by["corpus_feedback"]
    assert inf[0]["t0"] <= cf["t0"] and inf[0]["t1"] <= cf["t1"]


# -- flight recorder: event log ----------------------------------------


def test_event_log_roundtrip_and_resume_seq(tmp_path):
    """Schema round-trip + seq monotonicity across a reopen (the
    --resume contract) + torn-tail tolerance."""
    d = str(tmp_path)
    log = EventLog(d)
    log.emit("new_path", md5="a" * 32, new_paths=1)
    log.emit("crash", md5="b" * 32, crashes=1, unique_crashes=1)
    log.close()
    recs = list(read_events(d))
    assert [r["seq"] for r in recs] == [0, 1]
    assert all(r["v"] == 1 and r["t"] > 0 for r in recs)
    assert recs[0]["type"] == "new_path" and recs[0]["md5"] == "a" * 32
    # torn tail: a record cut mid-append is skipped, not fatal
    with open(os.path.join(d, "events.jsonl"), "a") as f:
        f.write('{"v": 1, "seq": 2, "t": 1.0, "ty')
    assert [r["seq"] for r in recs] == \
        [r["seq"] for r in read_events(d)]
    assert last_event_seq(d) == 1
    # a reopened log (resume) continues the monotone seq
    log2 = EventLog(d)
    assert log2.next_seq == 2
    log2.emit("plateau", execs=100)
    log2.close()
    seqs = [r["seq"] for r in read_events(d)]
    assert seqs == sorted(seqs) == [0, 1, 2]
    # cursor reads skip already-seen records
    assert [r["seq"] for r in read_events(d, since_seq=1)] == [2]
    assert [r["type"] for r in read_events(d, types=["crash"])] \
        == ["crash"]
    # a parseable line with a non-numeric seq (foreign writer /
    # corruption) is skipped, not fatal
    with open(os.path.join(d, "events.jsonl"), "a") as f:
        f.write('{"v": 1, "seq": null, "t": 1.0, "type": "crash"}\n')
    assert [r["seq"] for r in read_events(d)] == [0, 1, 2]


def test_event_log_and_trace_absorb_non_json_fields(tmp_path):
    """Observability must never kill the campaign: a numpy scalar or
    bytes field neither raises from emit() nor from the trace export
    (it stringifies)."""
    import numpy as np
    log = EventLog(str(tmp_path))
    log.emit("new_path", count=np.int64(5), raw=b"\x01")
    log.close()
    (rec,) = read_events(str(tmp_path))
    assert rec["count"] == "5"           # stringified, not lost
    tr = TraceRecorder()
    tr.instant("plateau", args={"execs": np.int64(7)})
    assert tr.export(str(tmp_path / "t.json"))
    json.load(open(tmp_path / "t.json"))


def test_event_log_write_failure_degrades(tmp_path, monkeypatch):
    log = EventLog(str(tmp_path))
    monkeypatch.setattr(
        "builtins.open",
        lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")))
    rec = log.emit("new_path", md5="x")   # warns, never raises
    assert rec["type"] == "new_path"      # in-process view intact
    assert log.last_times["new_path"] == rec["t"]


def test_event_log_rotation_caps_size_and_keeps_seq(tmp_path):
    """--events-max-mb: the live file rotates to events.jsonl.1 when
    it crosses the cap, seq stays monotone across rotations, readers
    see one seamless stream (rotated tail first) and a resumed log
    anchors past the rotated generation."""
    d = str(tmp_path)
    log = EventLog(d, max_bytes=600)     # a few records per file
    for i in range(30):
        log.emit("new_path", md5="%032x" % i)
    log.close()
    assert log.rotations >= 1
    live = os.path.join(d, "events.jsonl")
    rotated = live + ".1"
    assert os.path.exists(rotated)
    # both generations stay under ~the cap (the live file may not
    # exist at all right after a rotation on the final record)
    assert os.path.getsize(rotated) < 600 + 200
    if os.path.exists(live):
        assert os.path.getsize(live) < 600 + 200
    # the combined stream is seq-ordered and gapless over the last
    # two generations
    seqs = [r["seq"] for r in read_events(d)]
    assert seqs == sorted(seqs)
    assert seqs[-1] == 29
    assert seqs == list(range(seqs[0], 30))
    # resume continues past the newest record, even if the live file
    # was JUST rotated (absent/empty) — the anchor falls back to the
    # .1 tail
    if os.path.exists(live):
        os.replace(live, rotated)
    log2 = EventLog(d)
    assert log2.next_seq == 30
    log2.emit("crash", md5="c" * 32, unique_crashes=1)
    log2.close()
    assert last_event_seq(d) == 30
    # a FRESH campaign clears both generations
    log3 = EventLog(d, fresh=True)
    log3.emit("new_path", md5="f" * 32)
    log3.close()
    assert not os.path.exists(rotated)
    assert [r["seq"] for r in read_events(d)] == [0]


def test_heartbeat_forwarder_survives_rotation(tmp_path,
                                               monkeypatch):
    """A rotation between beats (live file shrinks below the cursor)
    drains the rotated generation's tail, then continues on the
    fresh live file — no terminal event is lost."""
    from killerbeez_tpu.manager import worker as w
    out = tmp_path / "o"
    out.mkdir()
    (out / "stats.jsonl").write_text(json.dumps(_snap(1)) + "\n")
    posts = []
    monkeypatch.setattr(
        w, "_request_retry",
        lambda url, payload=None, **kw: posts.append((url, payload)))
    hb = w.Heartbeat("http://mgr", "7", "w1", str(out), interval=99)
    log = EventLog(str(out), max_bytes=1 << 20)  # no auto-rotation
    log.emit("crash", md5="a" * 32, unique_crashes=1)
    hb.beat()
    log.emit("crash", md5="b" * 32, unique_crashes=2)
    log._rotate()                        # rotate with b unforwarded
    log.emit("crash", md5="c" * 32, unique_crashes=3)
    log.close()
    hb.beat()
    sent = [e["md5"] for _, p in posts if p and "events" in p
            for e in p["events"]]
    assert sent == ["a" * 32, "b" * 32, "c" * 32]
    assert hb.events_sent == 3
    # a rotation that lands BEFORE the first beat (startup crash
    # storm) is drained too: a fresh Heartbeat forwards the .1
    # generation up front, then the live file
    posts.clear()
    hb2 = w.Heartbeat("http://mgr", "7", "w2", str(out), interval=99)
    hb2.beat()
    sent = [e["md5"] for _, p in posts if p and "events" in p
            for e in p["events"]]
    assert sent == ["a" * 32, "b" * 32, "c" * 32]


def _rand_events(rng, worker):
    return [{"v": 1, "seq": i, "t": rng.uniform(0, 100),
             "worker": worker,
             "type": rng.choice(["crash", "hang", "plateau"])}
            for i in range(rng.randrange(0, 6))]


def test_merge_events_associative_commutative_deduped():
    rng = random.Random(0xf11e)
    for _ in range(30):
        a, b, c = (_rand_events(rng, w) for w in "abc")
        assert merge_events(a, b) == merge_events(b, a)
        assert merge_events(merge_events(a, b), c) == \
            merge_events(a, merge_events(b, c))
        # exact duplicates (a replayed heartbeat window) collapse
        assert merge_events(a, a) == merge_events(a, [])
    # snapshots carrying event lists fold through merge_two/merge
    sa = {"counters": {"execs": 1}, "events": [
        {"v": 1, "seq": 0, "t": 2.0, "worker": "w1", "type": "crash"}]}
    sb = {"counters": {"execs": 2}, "events": [
        {"v": 1, "seq": 0, "t": 1.0, "worker": "w2", "type": "hang"}]}
    m = merge([sa, sb])
    assert m["counters"]["execs"] == 3
    assert [e["worker"] for e in m["events"]] == ["w2", "w1"]  # by t
    assert merge([sa, sb])["events"] == merge([sb, sa])["events"]


def test_fuzzer_stats_carries_last_find_epochs(tmp_path):
    """AFL's last_path/last_crash/last_hang fields, sourced from the
    find-recency gauges the event tier stamps."""
    snap = _snap(100)
    snap["gauges"] = {"last_path": 1234.9, "last_crash": 99.2}
    path = str(tmp_path / "fuzzer_stats")
    write_fuzzer_stats(path, snap)
    fs = parse_fuzzer_stats(path)
    assert fs["last_path"] == "1234"
    assert fs["last_crash"] == "99"
    assert fs["last_hang"] == "0"        # never seen: AFL's 0


def test_telemetry_event_stamps_gauges_and_log(tmp_path):
    tl = Telemetry(str(tmp_path / "o"), interval_s=0.0, trace=True)
    tl.event("new_path", md5="a" * 32, new_paths=1)
    tl.event("crash", md5="b" * 32, crashes=1, unique_crashes=1)
    tl.event("sync_round", pushed=1, pulled=0)
    assert tl.registry.gauges["last_path"] > 0
    assert tl.registry.gauges["last_crash"] > 0
    assert "last_hang" not in tl.registry.gauges
    types = [r["type"] for r in read_events(str(tmp_path / "o"))]
    assert types == ["new_path", "crash", "sync_round"]
    # events also drop instant marks on the span timeline
    marks = [e for e in tl.trace.to_chrome()["traceEvents"]
             if e["ph"] == "i"]
    assert [m["name"] for m in marks] == types
    # file-less telemetry: gauges still stamp, nothing is written
    tl2 = Telemetry(None)
    tl2.event("new_path", md5="c" * 32)
    assert tl2.registry.gauges["last_path"] > 0
    assert tl2.events is None


# -- kb-timeline --------------------------------------------------------


def _chrome_doc(spans, instants=()):
    """Synthetic Chrome trace from (name, tid, t0_us, t1_us) spans."""
    evs = []
    for name, tid, t0, t1 in spans:
        evs.append({"ph": "B", "name": name, "pid": 1, "tid": tid,
                    "ts": t0})
        evs.append({"ph": "E", "name": name, "pid": 1, "tid": tid,
                    "ts": t1})
    for name, tid, ts in instants:
        evs.append({"ph": "i", "name": name, "pid": 1, "tid": tid,
                    "ts": ts, "s": "t"})
    evs.sort(key=lambda e: e["ts"])
    return {"traceEvents": evs, "displayTimeUnit": "ms",
            "otherData": {"wall_t0": 1000.0}}


def test_timeline_stage_quantiles_nearest_rank():
    """p50/p90/p99 over span durations use ceil-based nearest rank —
    a floor over n-1 would report the MINIMUM as the p99 of a 2-span
    stage."""
    from killerbeez_tpu.tools.timeline_tool import stage_report
    spans = [{"name": "s", "tid": 0, "t0": 0.0, "t1": 100.0},
             {"name": "s", "tid": 0, "t0": 200.0, "t1": 1100.0}]
    st, _ = stage_report(spans)
    assert st["s"]["p50_us"] == 100.0
    assert st["s"]["p90_us"] == 900.0    # the tail, not the min
    assert st["s"]["p99_us"] == 900.0
    spans10 = [{"name": "s", "tid": 0, "t0": 0.0, "t1": float(i + 1)}
               for i in range(10)]
    st, _ = stage_report(spans10)
    assert st["s"]["p50_us"] == 5.0
    assert st["s"]["p90_us"] == 9.0
    assert st["s"]["p99_us"] == 10.0     # ceil(9.9)-1 = the max


def test_timeline_detects_host_bound_bubble(tmp_path):
    """A deliberately host-bound timeline: steady 1ms dispatch cadence,
    then a 40ms gap filled by triage — exactly one bubble, attributed
    to triage."""
    from killerbeez_tpu.tools import timeline_tool as tt
    spans = []
    t = 0.0
    for i in range(10):                  # steady cadence: 1ms period
        spans.append(("execute", i % 4, t, t + 500.0))
        t += 1000.0
    gap_start = t - 500.0                # device idle from last end
    spans.append(("triage", 0, gap_start + 100.0,
                  gap_start + 39000.0))  # host busy through the gap
    t = gap_start + 40000.0
    spans.append(("execute", 0, t, t + 500.0))
    doc = _chrome_doc(spans)
    parsed = tt.spans_from_chrome(doc)
    assert len(parsed) == len(spans)
    bubbles, thresh = tt.detect_bubbles(parsed)
    assert len(bubbles) == 1
    assert bubbles[0]["dominant_stage"] == "triage"
    assert bubbles[0]["duration_us"] == pytest.approx(40000.0)
    assert thresh < 40000.0
    # steady cadence alone: no bubbles
    steady = tt.spans_from_chrome(_chrome_doc(
        [("execute", 0, i * 1000.0, i * 1000.0 + 500.0)
         for i in range(10)]))
    assert tt.detect_bubbles(steady)[0] == []
    # an idle gap with NO host span active is not a host bubble
    no_host = tt.spans_from_chrome(_chrome_doc(
        [("execute", 0, i * 1000.0, i * 1000.0 + 500.0)
         for i in range(10)]
        + [("execute", 0, 50000.0, 50500.0)]))
    assert tt.detect_bubbles(no_host)[0] == []


def test_timeline_report_and_cli(tmp_path, capsys):
    from killerbeez_tpu.tools import timeline_tool as tt
    out = tmp_path / "out"
    out.mkdir()
    doc = _chrome_doc(
        [("execute", 0, 0.0, 600.0), ("triage", 0, 700.0, 900.0),
         ("execute", 1, 1000.0, 1600.0), ("in_flight", 1, 1600.0,
                                          1900.0)],
        instants=[("new_path", 0, 800.0)])
    (out / "trace.json").write_text(json.dumps(doc))
    log = EventLog(str(out))
    log.emit("new_path", md5="a" * 32, new_paths=1)
    log.close()
    write_fuzzer_stats(str(out / "fuzzer_stats"),
                       {**_snap(100, paths=1),
                        "counters": {"execs": 100, "new_paths": 1}})
    assert tt.main([str(out)]) == 0
    text = capsys.readouterr().out
    assert "per-stage wall clock" in text
    assert "reconcile     : OK" in text
    assert "batch-" not in text          # synthetic doc: unnamed lanes
    assert tt.main([str(out), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["stages"]["execute"]["count"] == 2
    assert rep["reconcile"]["ok"] is True
    assert rep["critical_path"] == "triage"
    # no artifacts at all: clean error exit
    empty = tmp_path / "none"
    empty.mkdir()
    assert tt.main([str(empty)]) == 1


def test_traced_campaign_end_to_end(tmp_path):
    """Acceptance slice: a --trace campaign on the `test` target
    emits a balanced trace.json + an events.jsonl that reconciles
    exactly with fuzzer_stats, and kb-timeline reads both."""
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory
    from killerbeez_tpu.tools import timeline_tool as tt

    out = str(tmp_path / "out")
    instr = instrumentation_factory("jit_harness",
                                    '{"target": "test"}')
    mut = mutator_factory("bit_flip", None, b"ABC@")
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=out, batch_size=8,
                stats_interval=0.0, trace=True)
    stats = fz.run(32)                   # full walk: 1 unique crash
    assert stats.unique_crashes == 1
    doc = json.load(open(os.path.join(out, "trace.json")))
    _balance_check(doc)
    # every pipeline stage left spans, on pipeline-slot lanes
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "B"}
    assert {"execute", "host_transfer", "triage"} <= names
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert any(l.startswith("batch-") for l in lanes)
    evs = list(read_events(out))
    fs = parse_fuzzer_stats(os.path.join(out, "fuzzer_stats"))
    n_paths = sum(1 for e in evs if e["type"] == "new_path")
    n_crash = sum(1 for e in evs if e["type"] == "crash")
    assert n_paths == int(fs["paths_total"]) == stats.new_paths
    assert n_crash == int(fs["unique_crashes"]) == 1
    assert int(fs["last_crash"]) > 0 and int(fs["last_path"]) > 0
    rep = tt.build_report(doc, evs, fs)
    assert rep["reconcile"]["ok"] is True
    assert rep["span_count"] > 0
    # --resume continues the monotone event seq; a fresh (non-resume)
    # campaign into the same dir truncates instead of inheriting the
    # old timeline (counters restart — stale events would break
    # reconciliation and re-forward old terminal events)
    def again(resume):
        fz = Fuzzer(driver_factory(
            "file", None,
            instrumentation_factory("jit_harness",
                                    '{"target": "test"}'),
            mutator_factory("bit_flip", None, b"ABC@")),
            output_dir=out, batch_size=8, stats_interval=0.0,
            trace=True, corpus_dir=str(tmp_path / "corpus"),
            resume=resume)
        fz.run(8)

    first_run_seqs = [e["seq"] for e in evs]
    again(resume=True)
    seqs = [e["seq"] for e in read_events(out)]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert len(seqs) > len(first_run_seqs)   # continued, not reset
    again(resume=False)
    seqs = [e["seq"] for e in read_events(out)]
    assert seqs and seqs[0] == 0             # truncated: new timeline
    assert len(seqs) < len(first_run_seqs) + 3


# -- manager /api/events exchange --------------------------------------


def test_manager_events_endpoint_cursor_and_dedup():
    from killerbeez_tpu.manager import ManagerServer
    import urllib.request
    s = ManagerServer(port=0)
    s.start()
    try:
        base = f"http://127.0.0.1:{s.port}/api/events/c1"

        def post(worker, events):
            req = urllib.request.Request(
                base, json.dumps({"worker": worker,
                                  "events": events}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read())

        def get(since=0):
            with urllib.request.urlopen(f"{base}?since={since}") as r:
                return json.loads(r.read())

        e0 = {"v": 1, "seq": 0, "t": 1.0, "type": "crash",
              "md5": "a" * 32}
        e1 = {"v": 1, "seq": 1, "t": 2.0, "type": "plateau"}
        assert post("w1", [e0, e1])["stored"] == 2
        # a retried window dedups on (worker, seq, t)
        assert post("w1", [e0, e1])["stored"] == 0
        assert post("w2", [e0])["stored"] == 1   # other worker: new
        # a same-named worker RESTARTED with a fresh log reuses seq 0
        # but carries a new wall time — its events must still store
        assert post("w1", [{**e0, "t": 50.0}])["stored"] == 1
        # malformed records are skipped, not fatal
        assert post("w1", [{"v": 1, "seq": None, "t": 1.0,
                            "type": "crash"}])["stored"] == 0
        view = get()
        # ids need not be dense (conflicting inserts may burn
        # AUTOINCREMENT values) — only the cursor contract matters
        assert view["latest"] == view["events"][-1]["id"]
        assert [r["worker"] for r in view["events"]] \
            == ["w1", "w1", "w2", "w1"]
        assert view["events"][0]["event"]["md5"] == "a" * 32
        # cursor semantics mirror /api/corpus
        tail = get(since=view["events"][1]["id"])
        assert [r["worker"] for r in tail["events"]] == ["w2", "w1"]
        assert get(since=view["latest"])["events"] == []
    finally:
        s.stop()


def test_heartbeat_forwards_terminal_events(tmp_path, monkeypatch):
    """The worker heartbeat tails events.jsonl and forwards crash/
    hang/plateau records (only complete lines, cursor advances, a
    failed POST rewinds for the next beat)."""
    from killerbeez_tpu.manager import worker as w
    out = tmp_path / "o"
    out.mkdir()
    (out / "stats.jsonl").write_text(json.dumps(_snap(1)) + "\n")
    log = EventLog(str(out))
    log.emit("new_path", md5="n" * 32)   # NOT terminal: filtered
    log.emit("crash", md5="c" * 32, crashes=1, unique_crashes=1)
    log.emit("plateau", execs=64)
    log.close()
    posts = []
    monkeypatch.setattr(
        w, "_request_retry",
        lambda url, payload=None, **kw: posts.append((url, payload)))
    hb = w.Heartbeat("http://mgr", "7", "w1", str(out), interval=99)
    assert hb.beat()
    ev_posts = [p for p in posts if "/api/events/" in p[0]]
    assert len(ev_posts) == 1
    url, payload = ev_posts[0]
    assert url == "http://mgr/api/events/7"
    assert [e["type"] for e in payload["events"]] \
        == ["crash", "plateau"]
    assert hb.events_sent == 2
    # nothing new: no second events POST
    posts.clear()
    hb.beat()
    assert not [p for p in posts if "/api/events/" in p[0]]
    # a torn tail line is left for the next beat
    with open(out / "events.jsonl", "a") as f:
        f.write('{"v": 1, "seq": 3, "t": 1.0, "type": "crash"')
    posts.clear()
    hb.beat()
    assert not [p for p in posts if "/api/events/" in p[0]]
    with open(out / "events.jsonl", "a") as f:
        f.write(', "md5": "d"}\n')
    posts.clear()
    hb.beat()
    (url, payload), = [p for p in posts if "/api/events/" in p[0]]
    assert payload["events"][0]["seq"] == 3
    # transport failure rewinds the cursor; the next beat re-sends
    log2 = EventLog(str(out))
    log2.emit("hang", md5="h" * 32)
    log2.close()

    def down(url, payload=None, **kw):
        if "/api/events/" in url:
            raise ConnectionError("refused")
        return None

    monkeypatch.setattr(w, "_request_retry", down)
    hb.beat()
    posts.clear()
    monkeypatch.setattr(
        w, "_request_retry",
        lambda url, payload=None, **kw: posts.append((url, payload)))
    hb.beat()
    (url, payload), = [p for p in posts if "/api/events/" in p[0]]
    assert [e["type"] for e in payload["events"]] == ["hang"]


def test_stats_tui_json_once(tmp_path, capsys):
    from killerbeez_tpu.tools import stats_tui
    snap = _snap(4096, paths=7)
    (tmp_path / "stats.jsonl").write_text(json.dumps(snap) + "\n")
    assert stats_tui.main([str(tmp_path), "--once", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["counters"]["execs"] == 4096
    # --json without --once is an argument error
    assert stats_tui.main([str(tmp_path), "--json"]) == 2


# -- Telemetry facade --------------------------------------------------


def test_telemetry_facade_and_fuzzstats_view(tmp_path):
    from killerbeez_tpu.fuzzer.loop import FuzzStats
    tl = Telemetry(str(tmp_path / "out"), interval_s=0.0)
    st = FuzzStats(tl.registry)
    st.iterations += 64                  # property writes hit the
    st.crashes += 1                      # registry directly
    assert tl.registry.counters["execs"] == 64
    assert tl.registry.counters["crashes"] == 1
    tl.registry.count("execs", 36)
    assert st.iterations == 100          # ...and reads see them
    d = st.as_dict()
    assert d["iterations"] == 100 and d["crashes"] == 1
    assert "execs_per_sec" in d and "execs_per_sec_ema" in d
    tl.flush()
    assert (tmp_path / "out" / "fuzzer_stats").exists()
    disabled = Telemetry(None)
    disabled.maybe_flush()               # no sink: clean no-op
    assert disabled.stage_summary() == ""
