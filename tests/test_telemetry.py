"""Telemetry subsystem tests — registry math (EMA decay, histogram
buckets), merge algebra (associative + commutative under random
snapshots), atomic fuzzer_stats writes (a reader never sees a torn
file), sink file formats, worker heartbeat retry/backoff, and the
kb-stats renderer."""

import json
import os
import random
import threading

import pytest

from killerbeez_tpu.telemetry import (
    MetricsRegistry, StageTimer, Telemetry, merge, merge_two,
    parse_fuzzer_stats,
)
from killerbeez_tpu.telemetry.metrics import (
    EmaRate, HIST_BUCKETS, Histogram,
)
from killerbeez_tpu.telemetry.sink import (
    PLOT_FIELDS, StatsSink, plot_row, write_fuzzer_stats,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- EMA rate ----------------------------------------------------------


def test_ema_rate_converges_to_steady_rate():
    clk = FakeClock()
    r = EmaRate(tau=10.0, time_fn=clk)
    r.add(0)                             # anchor t0
    for _ in range(200):                 # 100/s steady stream
        clk.advance(1.0)
        r.add(100)
    assert r.rate == pytest.approx(100.0, rel=0.01)
    assert 0.99 < r.weight <= 1.0


def test_ema_rate_decays_toward_recent_rate():
    clk = FakeClock()
    r = EmaRate(tau=5.0, time_fn=clk)
    r.add(0)
    for _ in range(50):
        clk.advance(1.0)
        r.add(1000)                      # fast phase: 1000/s
    fast = r.rate
    for _ in range(50):
        clk.advance(1.0)
        r.add(10)                        # slow phase: 10/s
    assert fast == pytest.approx(1000.0, rel=0.05)
    assert r.rate == pytest.approx(10.0, rel=0.05)  # forgot the past


def test_ema_rate_first_sample_only_anchors():
    clk = FakeClock()
    r = EmaRate(time_fn=clk)
    r.add(500)
    assert r.rate == 0.0 and r.weight == 0.0


# -- histogram ---------------------------------------------------------


def test_histogram_bucket_edges_inclusive():
    h = Histogram()
    h.observe(HIST_BUCKETS[0])           # == first edge -> bucket 0
    h.observe(HIST_BUCKETS[0] * 1.001)   # just above -> bucket 1
    h.observe(HIST_BUCKETS[-1] * 2)      # beyond all edges -> overflow
    assert h.counts[0] == 1
    assert h.counts[1] == 1
    assert h.counts[-1] == 1
    assert h.total == 3
    assert h.sum == pytest.approx(
        HIST_BUCKETS[0] * 2.001 + HIST_BUCKETS[-1] * 2)


def test_histogram_matches_linear_scan():
    rng = random.Random(7)
    h = Histogram()
    vals = [rng.uniform(0, 1e-1) for _ in range(500)]
    for v in vals:
        h.observe(v)
    for i, edge in enumerate(HIST_BUCKETS):
        lo = HIST_BUCKETS[i - 1] if i else float("-inf")
        want = sum(1 for v in vals if lo < v <= edge)
        assert h.counts[i] == want, f"bucket {i}"


# -- registry + stage timer -------------------------------------------


def test_registry_counters_and_run_windows():
    clk = FakeClock()
    reg = MetricsRegistry(time_fn=clk)
    reg.count("execs", 100)
    reg.count("execs", 28)
    clk.advance(100.0)                   # idle gap: not active time
    reg.run_started()
    clk.advance(4.0)
    reg.run_ended()
    assert reg.counters["execs"] == 128
    assert reg.active_seconds() == pytest.approx(4.0)
    assert reg.execs_per_sec() == pytest.approx(32.0)  # active, not age
    assert reg.elapsed() == pytest.approx(104.0)


def test_stage_timer_records_histogram_and_total():
    reg = MetricsRegistry()
    t = StageTimer(reg)
    with t("triage"):
        pass
    with t("triage"):
        with t("fs_write"):              # spans nest
            pass
    assert reg.hists["triage"].total == 2
    assert reg.hists["fs_write"].total == 1
    assert reg.counters["triage_seconds"] >= 0
    split = reg.stage_split()
    assert set(split) <= {"triage", "fs_write"}
    assert sum(split.values()) == pytest.approx(1.0)


def test_snapshot_shape_round_trips_json():
    reg = MetricsRegistry()
    reg.count("execs", 5)
    reg.gauge("corpus_size", 3)
    reg.rate("execs", 5)
    reg.observe("execute", 0.01)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["counters"]["execs"] == 5
    assert snap["gauges"]["corpus_size"] == 3
    assert "execs" in snap["rates"]
    assert "execute" in snap["hists"]
    assert "execs_per_sec" in snap["derived"]


# -- merge algebra -----------------------------------------------------


def _rand_snapshot(rng):
    names = ["execs", "crashes", "new_paths", "hangs"]
    return {
        "t": rng.uniform(1000, 2000),
        "start_time": rng.uniform(0, 1000),
        "counters": {n: rng.randrange(0, 10000)
                     for n in rng.sample(names, rng.randrange(1, 4))},
        "gauges": {n: rng.uniform(0, 50)
                   for n in rng.sample(["corpus_size", "depth"],
                                       rng.randrange(0, 3))},
        "rates": {n: {"rate": rng.uniform(0, 1e6),
                      "weight": rng.uniform(0, 1)}
                  for n in rng.sample(names, rng.randrange(0, 3))},
        "hists": {n: {"counts": [rng.randrange(0, 9)
                                 for _ in range(4)],
                      "total": rng.randrange(0, 30),
                      "sum": rng.uniform(0, 5)}
                  for n in rng.sample(["execute", "triage"],
                                      rng.randrange(0, 3))},
    }


def _assert_snap_equal(a, b):
    assert a["counters"] == pytest.approx(b["counters"])
    assert a["gauges"] == pytest.approx(b["gauges"])
    assert set(a["rates"]) == set(b["rates"])
    for k in a["rates"]:
        assert a["rates"][k]["rate"] == \
            pytest.approx(b["rates"][k]["rate"])
        assert a["rates"][k]["weight"] == \
            pytest.approx(b["rates"][k]["weight"])
    assert set(a["hists"]) == set(b["hists"])
    for k in a["hists"]:
        assert a["hists"][k]["counts"] == b["hists"][k]["counts"]
        assert a["hists"][k]["total"] == b["hists"][k]["total"]
    assert a.get("t") == pytest.approx(b.get("t"))
    assert a.get("start_time") == pytest.approx(b.get("start_time"))


def test_merge_is_associative_and_commutative():
    rng = random.Random(0xbee5)
    for _ in range(40):                  # property check over randoms
        a, b, c = (_rand_snapshot(rng) for _ in range(3))
        _assert_snap_equal(merge_two(a, b), merge_two(b, a))
        _assert_snap_equal(merge_two(merge_two(a, b), c),
                           merge_two(a, merge_two(b, c)))
        _assert_snap_equal(merge([a, b, c]), merge([c, b, a]))


def test_shard_stat_snapshots_fold():
    """The mesh campaign's per-epoch fold: dp shards' snapshots merge
    into the fleet view (execs sum across shards, step clock max's)."""
    from killerbeez_tpu.parallel.distributed import (
        shard_stat_snapshots,
    )

    class FakeMesh:
        shape = {"dp": 4, "mp": 2}

    snaps = shard_stat_snapshots(FakeMesh(), 16, 3)
    assert len(snaps) == 4               # one per dp shard
    m = merge(snaps)
    assert m["counters"]["execs"] == 64  # 4 shards x 16 lanes
    assert m["gauges"]["shard_step"] == 3
    assert m["gauges"]["lanes_per_shard"] == 16
    # epoch folds accumulate associatively into the campaign total
    acc = merge_two(m, merge(shard_stat_snapshots(FakeMesh(), 16, 4)))
    assert acc["counters"]["execs"] == 128
    assert acc["gauges"]["shard_step"] == 4


def test_merge_semantics():
    a = {"counters": {"execs": 100, "crashes": 1},
         "gauges": {"corpus_size": 5},
         "rates": {"execs": {"rate": 1000.0, "weight": 1.0}}}
    b = {"counters": {"execs": 50},
         "gauges": {"corpus_size": 9},
         "rates": {"execs": {"rate": 400.0, "weight": 0.5}}}
    m = merge([a, b])
    assert m["counters"]["execs"] == 150         # summed
    assert m["counters"]["crashes"] == 1
    assert m["gauges"]["corpus_size"] == 9       # max
    # weight-weighted mean: (1000*1 + 400*0.5) / 1.5
    assert m["rates"]["execs"]["rate"] == pytest.approx(800.0)
    assert m["rates"]["execs"]["weight"] == pytest.approx(1.5)
    assert merge([]) is None


# -- sink: atomicity + formats ----------------------------------------


def _snap(execs, paths=0, t=1000.0):
    return {"t": t, "start_time": 0.0, "elapsed": t,
            "counters": {"execs": execs, "new_paths": paths},
            "gauges": {}, "rates": {},
            "derived": {"execs_per_sec": execs / t,
                        "execs_per_sec_ema": 0.0}}


def test_fuzzer_stats_write_is_atomic_under_reader(tmp_path):
    """A tailer hammering the file during 200 rewrites must always
    parse a COMPLETE snapshot — os.replace publishes whole files
    only (the torn-write guarantee external dashboards rely on)."""
    path = str(tmp_path / "fuzzer_stats")
    write_fuzzer_stats(path, _snap(0))
    keys = set(parse_fuzzer_stats(path))
    stop = threading.Event()
    torn = []

    def reader():
        while not stop.is_set():
            got = parse_fuzzer_stats(path)
            if set(got) != keys or not all(v for v in got.values()):
                torn.append(got)

    th = threading.Thread(target=reader)
    th.start()
    try:
        for i in range(1, 201):
            write_fuzzer_stats(path, _snap(i * 1000, paths=i))
    finally:
        stop.set()
        th.join()
    assert not torn, torn[:3]
    assert not os.path.exists(path + ".tmp")  # tmp never left behind
    assert parse_fuzzer_stats(path)["execs_done"] == "200000"


def test_failed_write_leaves_previous_stats_intact(tmp_path,
                                                   monkeypatch):
    path = str(tmp_path / "fuzzer_stats")
    write_fuzzer_stats(path, _snap(42))
    real_replace = os.replace

    def boom(src, dst):
        raise OSError("disk full")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        write_fuzzer_stats(path, _snap(999))
    monkeypatch.setattr(os, "replace", real_replace)
    assert parse_fuzzer_stats(path)["execs_done"] == "42"


def test_sink_files_and_plot_monotone(tmp_path):
    clk = FakeClock()
    reg = MetricsRegistry(time_fn=clk)
    sink = StatsSink(str(tmp_path), reg, interval_s=10.0)
    for i in range(5):
        reg.count("execs", 100)
        reg.count("new_paths", 2)
        clk.advance(11.0)
        assert sink.maybe_flush()
        assert not sink.maybe_flush()    # within the interval: no-op
    rows = [r for r in
            (tmp_path / "plot_data").read_text().splitlines()
            if not r.startswith("#")]
    assert len(rows) == 5
    execs = [int(r.split(",")[1]) for r in rows]
    assert execs == sorted(execs)        # monotone cumulative
    assert execs[-1] == 500
    jl = [json.loads(l) for l in
          (tmp_path / "stats.jsonl").read_text().splitlines()]
    assert len(jl) == 5
    assert jl[-1]["counters"]["execs"] == 500
    stats = parse_fuzzer_stats(str(tmp_path / "fuzzer_stats"))
    assert stats["execs_done"] == "500"
    assert stats["paths_total"] == "10"
    assert len(plot_row(_snap(1)).split(", ")) == len(PLOT_FIELDS)


# -- worker heartbeat retry -------------------------------------------


def test_request_retry_backs_off_then_succeeds(monkeypatch):
    from killerbeez_tpu.manager import worker as w
    calls = {"n": 0}
    sleeps = []

    def flaky(url, payload=None, method="POST"):
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("refused")
        return {"ok": True}

    monkeypatch.setattr(w, "_request", flaky)
    monkeypatch.setattr(w.time, "sleep", sleeps.append)
    assert w._request_retry("http://x/api", {}) == {"ok": True}
    assert calls["n"] == 3
    assert sleeps == [0.5, 1.0]          # exponential backoff


def test_request_retry_exhausts_and_raises(monkeypatch):
    from killerbeez_tpu.manager import worker as w

    def down(url, payload=None, method="POST"):
        raise ConnectionError("refused")

    monkeypatch.setattr(w, "_request", down)
    monkeypatch.setattr(w.time, "sleep", lambda s: None)
    with pytest.raises(ConnectionError):
        w._request_retry("http://x/api", {}, attempts=4)


def test_heartbeat_reads_latest_snapshot(tmp_path, monkeypatch):
    from killerbeez_tpu.manager import worker as w
    out = tmp_path / "output"
    out.mkdir()
    assert w.read_latest_snapshot(str(out)) is None   # no file yet
    with open(out / "stats.jsonl", "w") as f:
        f.write(json.dumps(_snap(100)) + "\n")
        f.write(json.dumps(_snap(900)) + "\n")
    assert w.read_latest_snapshot(str(out))["counters"]["execs"] == 900
    posts = []
    monkeypatch.setattr(
        w, "_request_retry",
        lambda url, payload=None, **kw: posts.append((url, payload)))
    hb = w.Heartbeat("http://mgr", "7", "w1", str(out), interval=99)
    assert hb.beat()
    (url, payload), = posts
    assert url == "http://mgr/api/stats/7"
    assert payload["worker"] == "w1"
    assert payload["snapshot"]["counters"]["execs"] == 900
    # a record torn mid-append falls back to the previous complete
    # one (the final heartbeat must never be dropped over a tail race)
    with open(out / "stats.jsonl", "a") as f:
        f.write(json.dumps(_snap(950))[:40])          # no newline, torn
    assert w.read_latest_snapshot(str(out))["counters"]["execs"] == 900
    # O(1) tail: only the last window bytes are read on a long stream
    with open(out / "stats.jsonl", "a") as f:
        f.write("\n")
        for i in range(2000):
            f.write(json.dumps(_snap(i)) + "\n")
    assert w.read_latest_snapshot(
        str(out), window=4096)["counters"]["execs"] == 1999


def test_heartbeat_survives_dead_manager(tmp_path, monkeypatch):
    from killerbeez_tpu.manager import worker as w
    out = tmp_path / "o"
    out.mkdir()
    (out / "stats.jsonl").write_text(json.dumps(_snap(1)) + "\n")

    def down(url, payload=None, **kw):
        raise ConnectionError("refused")

    monkeypatch.setattr(w, "_request_retry", down)
    hb = w.Heartbeat("http://gone", "1", "w", str(out), interval=99)
    assert hb.beat() is False            # warns, never raises


# -- kb-stats renderer -------------------------------------------------


def test_stats_tui_render_and_once(tmp_path, capsys):
    from killerbeez_tpu.tools import stats_tui
    snap = _snap(1_500_000, paths=42, t=3700.0)
    snap["counters"].update(crashes=3, unique_crashes=2,
                            execute_seconds=8.0, triage_seconds=2.0)
    snap["gauges"] = {"corpus_size": 42, "pipeline_depth": 24}
    frame = stats_tui.render(snap)
    assert "1.50M" in frame              # execs humanized
    assert "01:01:40" in frame           # 3700s
    assert "crashes" in frame and "(2 unique)" in frame
    assert "stage split" in frame
    assert "execute" in frame and "80.0%" in frame
    # --once against a real stats.jsonl
    (tmp_path / "stats.jsonl").write_text(json.dumps(snap) + "\n")
    assert stats_tui.main([str(tmp_path), "--once"]) == 0
    assert "1.50M" in capsys.readouterr().out
    # missing file: clean nonzero exit, no traceback
    assert stats_tui.main([str(tmp_path / "nope"), "--once"]) == 1


def test_stats_tui_reads_manager_merge(tmp_path):
    from killerbeez_tpu.manager import ManagerServer
    from killerbeez_tpu.tools.stats_tui import read_manager
    s = ManagerServer(port=0)
    s.start()
    try:
        s.db.upsert_campaign_stats("c1", "w1", _snap(100))
        s.db.upsert_campaign_stats("c1", "w2", _snap(50))
        merged = read_manager(f"http://127.0.0.1:{s.port}", "c1")
    finally:
        s.stop()
    assert merged["counters"]["execs"] == 150
    assert merged["_n_workers"] == 2


# -- Telemetry facade --------------------------------------------------


def test_telemetry_facade_and_fuzzstats_view(tmp_path):
    from killerbeez_tpu.fuzzer.loop import FuzzStats
    tl = Telemetry(str(tmp_path / "out"), interval_s=0.0)
    st = FuzzStats(tl.registry)
    st.iterations += 64                  # property writes hit the
    st.crashes += 1                      # registry directly
    assert tl.registry.counters["execs"] == 64
    assert tl.registry.counters["crashes"] == 1
    tl.registry.count("execs", 36)
    assert st.iterations == 100          # ...and reads see them
    d = st.as_dict()
    assert d["iterations"] == 100 and d["crashes"] == 1
    assert "execs_per_sec" in d and "execs_per_sec_ema" in d
    tl.flush()
    assert (tmp_path / "out" / "fuzzer_stats").exists()
    disabled = Telemetry(None)
    disabled.maybe_flush()               # no sink: clean no-op
    assert disabled.stage_summary() == ""
