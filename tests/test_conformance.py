"""Counterexample-guided proxy conformance (ISSUE 18).

Covers the whole pipeline: the ``kbz-proxy-gap-v1`` emit→parse
round-trip property (byte soup + framed message trains), PR 17
backcompat, the bounded GapIndex (dedup / retention / manifest
rebuild), replay clustering, divergence localization (the blame must
land on the ACTUAL differing guard — looked up from dataflow, never
hardcoded), verified repair under the honesty contract (out-of-model
gaps stay ``unrepairable`` with a machine-readable reason), the
conformance lint tier (backlog warning / drift error + SARIF source
anchoring), the corpus repair-verdict sidecar bounds, and the
``--auto-repair`` plateau stage.  Native-substrate e2e rides the
``corpus_bin`` fixture and skips cleanly without the toolchain.
"""

import hashlib
import json
import random

import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_ERROR, FUZZ_HANG, FUZZ_NONE
from killerbeez_tpu.analysis.conformance import (
    BLAME_SCHEMA, GapParseError, conformance_lint, load_gap_reports,
    localize, parse_gap_report, replay_gaps, verdict_class,
)
from killerbeez_tpu.analysis.dataflow import analyze_dataflow
from killerbeez_tpu.analysis.repair import (
    Obligation, apply_patch, certification_obligations,
    enumerate_patches, run_repair, save_patched_program,
    verify_program, write_repair_ledger,
)
from killerbeez_tpu.analysis.solver import concrete_run
from killerbeez_tpu.corpus.quarantine import EntryValidator
from killerbeez_tpu.corpus.store import CorpusEntry, CorpusStore
from killerbeez_tpu.hybrid.gaps import (
    GapIndex, append_ledger, load_ledger, make_gap_report,
    proxy_trace_edge,
)
from killerbeez_tpu.hybrid.registry import (
    CertificationError, NativeSpec, ProxyBinding, get_binding,
    install_repaired,
)
from killerbeez_tpu.models.targets import get_target, load_program_file
from killerbeez_tpu.stateful.framing import frame_messages
from killerbeez_tpu.utils.fileio import md5_hex


def _mk_report(buf, *, binding="test_safe", kind="crash",
               proxy_status=FUZZ_CRASH, statuses=(0, 0, 0), t=1.0,
               program=None, **over):
    kw = dict(
        md5=md5_hex(buf), kind=kind, binding=binding,
        proxy_target="test", proxy_status=proxy_status,
        native_argv=["corpus/build/hybrid-safe"],
        native_delivery="stdin", statuses=list(statuses),
        repro=statuses.count(FUZZ_CRASH), repeats=len(statuses),
        t=t, input_bytes=buf,
        edge=(proxy_trace_edge(program, buf)
              if program is not None else None))
    kw.update(over)
    return make_gap_report(**kw)


# -- emit→parse round-trip property (byte soup + framed trains) --------


def _soups():
    rng = random.Random(0x18c0de)
    yield b""
    yield b"ABCD"
    yield b"\x00" * 9
    yield bytes(range(256))
    for n in (1, 7, 63, 255, 300, 1024):
        yield bytes(rng.randrange(256) for _ in range(n))
    # framed message trains are just bytes to the gap contract
    yield frame_messages([b"Lpw", b"", b"QA\xff"], 4)
    yield frame_messages([bytes(rng.randrange(256)
                                for _ in range(rng.randrange(5)))
                          for _ in range(6)], 8)


def test_gap_report_roundtrip_property():
    """make_gap_report -> parse_gap_report is the identity on every
    field the repair pass consumes, for arbitrary byte soup."""
    prog = get_target("test")
    for i, buf in enumerate(_soups()):
        statuses = [FUZZ_NONE, FUZZ_CRASH, FUZZ_ERROR][: 1 + i % 3]
        rep = _mk_report(buf, statuses=statuses, t=float(i),
                         program=prog)
        gap = parse_gap_report(rep)
        assert gap.md5 == md5_hex(buf)
        assert gap.input == buf
        assert gap.binding == "test_safe"
        assert gap.proxy_status == FUZZ_CRASH
        assert gap.native_statuses == statuses
        assert gap.t == float(i)
        assert gap.edge == proxy_trace_edge(prog, buf)
        assert gap.proxy_cls == "crash"


def test_gap_report_input_size_bound():
    """Oversized inputs are never inlined — the report still parses,
    counted unreplayable."""
    rep = _mk_report(b"x" * ((1 << 16) + 1))
    assert "input_hex" not in rep and rep["input_omitted"] > 1 << 16
    gap = parse_gap_report(rep)
    assert gap.input is None


def test_pr17_shaped_report_backcompat():
    """A PR 17-era report (no input_hex, no proxy.edge) parses; the
    replay pass counts it skipped — never silently dropped."""
    old = {
        "schema": "kbz-proxy-gap-v1", "md5": "a" * 32,
        "kind": "crash", "binding": "test_safe",
        "proxy": {"target": "test", "status": FUZZ_CRASH},
        "native": {"argv": ["x"], "delivery": "stdin",
                   "statuses": [0, 0, 0], "repro": 0, "repeats": 3},
        "t": 123.0,
    }
    gap = parse_gap_report(old)
    assert gap.input is None and gap.edge is None
    assert gap.native_cls == "ok" and gap.proxy_cls == "crash"
    replay = replay_gaps(get_target("test"), [gap])
    assert not replay.clusters
    assert replay.skipped == [(gap, "no-input")]


@pytest.mark.parametrize("mutate,reason", [
    (dict(schema="kbz-proxy-gap-v0"), "gap:schema"),
    (dict(md5=""), "gap:md5"),
    (dict(kind="banana"), "gap:kind"),
    (dict(binding=7), "gap:binding"),
    (dict(proxy={"target": "test"}), "gap:proxy"),
    (dict(native="nope"), "gap:native"),
    (dict(native={"statuses": "all-fine"}), "gap:native.statuses"),
    (dict(t="yesterday"), "gap:t"),
    (dict(input_hex="zz"), "gap:input_hex"),
])
def test_parse_rejects_are_machine_greppable(mutate, reason):
    rep = _mk_report(b"ABCD")
    rep.update(mutate)
    with pytest.raises(GapParseError, match=reason):
        parse_gap_report(rep)


def test_parse_rejects_bad_edge():
    rep = _mk_report(b"ABCD")
    rep["proxy"]["edge"] = [1, "two"]
    with pytest.raises(GapParseError, match="gap:proxy.edge"):
        parse_gap_report(rep)


def test_native_cls_majority_excludes_errors():
    gap = parse_gap_report(_mk_report(
        b"Q", statuses=[FUZZ_ERROR, FUZZ_NONE, FUZZ_NONE, FUZZ_CRASH]))
    assert gap.native_cls == "ok"
    all_err = parse_gap_report(_mk_report(
        b"Q", statuses=[FUZZ_ERROR, FUZZ_ERROR]))
    assert all_err.native_cls is None
    assert replay_gaps(get_target("test"), [all_err]).skipped[0][1] \
        == "native-never-measured"


def test_verdict_class_vocabulary():
    assert [verdict_class(s) for s in
            (FUZZ_NONE, FUZZ_HANG, FUZZ_CRASH, FUZZ_ERROR)] == \
        ["ok", "hang", "crash", "error"]


# -- bounded gap directory (GapIndex) ----------------------------------


def test_gap_index_dedup_by_edge_kind_md5(tmp_path):
    d = str(tmp_path / "gaps")
    idx = GapIndex(d)
    rep = _mk_report(b"ABCD", program=get_target("test"))
    assert idx.admit(rep) is not None
    assert idx.admit(rep) is None           # exact duplicate
    assert idx.duplicates == 1
    assert len(idx.entries) == 1
    # same input, different kind -> a distinct counterexample
    rep2 = dict(rep, kind="hang")
    assert idx.admit(rep2) is not None
    assert len(idx.entries) == 2


def test_gap_index_cap_evicts_oldest(tmp_path):
    d = str(tmp_path / "gaps")
    idx = GapIndex(d, cap=3)
    bufs = [bytes([i]) * 4 for i in range(5)]
    for i, buf in enumerate(bufs):
        idx.admit(_mk_report(buf, t=float(i)))
    assert len(idx.entries) == 3 and idx.evicted == 2
    kept = {e["md5"] for e in idx.entries}
    assert kept == {md5_hex(b) for b in bufs[2:]}
    # evicted report FILES are gone too
    files = {p.name for p in (tmp_path / "gaps").glob("*.json")}
    assert f"{md5_hex(bufs[0])}.json" not in files
    # the manifest is an honest ledger of the bound
    doc = json.loads((tmp_path / "gaps" / "index.json").read_text())
    assert doc["schema"] == "kbz-proxy-gap-index-v1"
    assert doc["evicted"] == 2 and len(doc["entries"]) == 3


def test_gap_index_rebuilds_from_torn_manifest(tmp_path):
    d = str(tmp_path / "gaps")
    idx = GapIndex(d)
    for buf in (b"one1", b"two2"):
        idx.admit(_mk_report(buf))
    (tmp_path / "gaps" / "index.json").write_text("{torn")
    again = GapIndex(d)
    assert {e["md5"] for e in again.entries} == \
        {md5_hex(b"one1"), md5_hex(b"two2")}
    # a PR 17-era dir (no manifest at all) also indexes on first touch
    (tmp_path / "gaps" / "index.json").unlink()
    assert len(GapIndex(d).entries) == 2


def test_ledger_roundtrip_bounded_and_torn(tmp_path):
    d = str(tmp_path / "gaps")
    assert load_ledger(d) == []
    for i in range(5):
        append_ledger(d, {"status": "repaired", "i": i}, cap=3)
    got = load_ledger(d)
    assert [r["i"] for r in got] == [2, 3, 4]
    (tmp_path / "gaps" / "repairs.json").write_text("not json")
    assert load_ledger(d) == []


def test_load_gap_reports_surfaces_rejects(tmp_path):
    d = tmp_path / "gaps"
    GapIndex(str(d)).admit(_mk_report(b"ABCD"))
    (d / "bogus.json").write_text("{")
    (d / "wrong.json").write_text(json.dumps({"schema": "nope"}))
    reports, rejects = load_gap_reports(str(d))
    assert len(reports) == 1
    assert sorted(r[0] for r in rejects) == ["bogus.json",
                                             "wrong.json"]


# -- replay clustering + localization ----------------------------------


def _d_check(program):
    """The ACTUAL differing guard of the test⇄hybrid-safe pair: the
    branch whose guarding constant is the 'D' byte — found from
    dataflow, never hardcoded."""
    facts = [f for f in analyze_dataflow(program).branches
             if f.const == ord("D")]
    assert len(facts) == 1
    return facts[0]


def _gap_corpus(tmp_path, program, bufs=(b"ABCD", b"ABCDxx",
                                         b"ABCD\x00\x01"),
                name="gaps"):
    d = str(tmp_path / name)
    idx = GapIndex(d)
    for buf in bufs:
        idx.admit(_mk_report(buf, program=program))
    return d


def test_replay_clusters_by_diverging_edge(tmp_path):
    program = get_target("test")
    d = _gap_corpus(tmp_path, program)
    reports, rejects = load_gap_reports(d)
    assert not rejects and len(reports) == 3
    replay = replay_gaps(program, reports)
    assert len(replay.clusters) == 1        # one diverging guard
    cl = replay.clusters[0]
    assert cl.proxy_cls == "crash" and cl.native_cls == "ok"
    assert len(cl.reports) == 3 == len(cl.traces)
    assert cl.edge == tuple(cl.traces[0].edges[-1])


def test_replay_stale_when_proxy_already_agrees():
    program = get_target("test")
    gap = parse_gap_report(_mk_report(
        b"NOPE", statuses=[FUZZ_NONE] * 3))   # proxy agrees: benign
    replay = replay_gaps(program, [gap])
    assert replay.stale == [gap] and not replay.clusters


def test_localize_blames_the_differing_guard(tmp_path):
    program = get_target("test")
    d = _gap_corpus(tmp_path, program)
    reports, _ = load_gap_reports(d)
    replay = replay_gaps(program, reports)
    blame = localize(program, replay.clusters[0])
    want = _d_check(program)
    assert blame.pc == want.pc
    assert blame.cmp == want.cmp
    assert blame.const == ord("D")
    assert blame.deps == sorted(want.deps)
    assert set(blame.inputs) == {md5_hex(b) for b in
                                 (b"ABCD", b"ABCDxx",
                                  b"ABCD\x00\x01")}
    # observed operands carry the concrete evidence: x == y == 'D'
    assert all(x == ord("D") for x, _y, _tk in blame.observed)
    rec = blame.as_dict()
    assert rec["schema"] == BLAME_SCHEMA
    assert rec["pc"] == want.pc and rec["candidates"][0] == want.pc


def test_localize_skips_constant_only_branches():
    """A trace whose only branches are input-independent yields no
    blame (None) — repair must report it, not guess."""
    from killerbeez_tpu.analysis.conformance import GapCluster
    program = get_target("test")
    trace = concrete_run(program, b"ABCD")
    facts = {f.pc: f for f in analyze_dataflow(program).branches}
    cluster = GapCluster(edge=(0, 1), proxy_cls="crash",
                        native_cls="ok", reports=[], traces=[trace])
    # with real facts the D-check wins; with every branch forced
    # constant there is nothing input-dependent to indict
    import killerbeez_tpu.analysis.conformance as conf
    blame = localize(program, cluster)
    assert blame is not None
    constant = {pc: type(f)(pc=f.pc, block=f.block, cmp=f.cmp,
                            const=f.const, deps=frozenset(),
                            always=f.always, len_dep=False)
                for pc, f in facts.items()}

    class _DF:
        branches = list(constant.values())
    assert localize(program, cluster, _DF()) is None
    assert conf._input_dependent(None) is True


# -- verified repair (the honesty contract) ----------------------------


def test_repair_e2e_in_process(tmp_path):
    """run_repair on the controlled gap corpus: localized to the
    D-check, patched, and the patch is verdict-identical to native
    on every gap input AND both certification seeds."""
    binding = get_binding("test_safe")
    program = binding.program()
    d = _gap_corpus(tmp_path, program)
    result, patched = run_repair(binding, d)
    assert result["status"] == "repaired", result
    assert patched is not None
    want = _d_check(program)
    [cl] = result["clusters"]
    assert cl["blame"]["pc"] == want.pc
    assert cl["status"] == "repaired" and cl["patch_desc"]
    # every gap input now classifies like the native tier (ok)...
    for buf in (b"ABCD", b"ABCDxx", b"ABCD\x00\x01"):
        assert verdict_class(concrete_run(patched, buf).status) == "ok"
    # ...and the benign certification seed kept its class
    assert verify_program(
        patched, certification_obligations(binding, program)) == []
    # the ORIGINAL program still crashes — repair copied, not mutated
    assert verdict_class(concrete_run(program, b"ABCD").status) \
        == "crash"


def test_repair_out_of_model_is_honestly_unrepairable(tmp_path):
    """A gap claiming the loop-free proxy should HANG has no patch in
    the typed space: verdict ``unrepairable``, machine-readable
    reason, NO best-effort program."""
    binding = get_binding("test")
    d = str(tmp_path / "gaps")
    GapIndex(d).admit(_mk_report(
        b"zzzz", binding="test", proxy_status=FUZZ_CRASH,
        statuses=[FUZZ_HANG] * 3))
    # the proxy is benign on zzzz: claim crash via a crashing input
    # replayed as hang-expected instead
    GapIndex(d).admit(_mk_report(
        b"ABCD", binding="test", statuses=[FUZZ_HANG] * 3))
    result, patched = run_repair(binding, d)
    assert result["status"] == "unrepairable"
    assert patched is None
    assert result["reason"]                  # machine-readable, always
    assert any(result["reason"].startswith(p)
               for p in ("patch:", "blame:", "verify:", "gap:"))


def test_repair_no_gaps_and_foreign_reports(tmp_path):
    binding = get_binding("test_safe")
    d = str(tmp_path / "gaps")
    result, patched = run_repair(binding, d)
    assert result["status"] == "no-gaps"
    assert result["reason"] == "gap:none-for-binding"
    # a foreign binding's reports are counted, never consumed
    GapIndex(d).admit(_mk_report(b"ABCD", binding="someone-else"))
    result, _ = run_repair(binding, d)
    assert result["status"] == "no-gaps" and result["foreign"] == 1


def test_repair_unreplayable_only_is_unrepairable(tmp_path):
    """Gap reports with no input bytes cannot anchor a repair: the
    verdict is unrepairable (gap:no-replayable-inputs), not no-gaps —
    there IS evidence, it just cannot be consumed."""
    binding = get_binding("test_safe")
    d = str(tmp_path / "gaps")
    rep = _mk_report(b"ABCD")
    del rep["input_hex"]
    GapIndex(d).admit(rep)
    result, patched = run_repair(binding, d)
    assert result["status"] == "unrepairable" and patched is None
    assert result["reason"] == "gap:no-replayable-inputs"


def test_patch_space_is_bounded_and_row_local():
    program = get_target("test")
    from killerbeez_tpu.analysis.repair import MAX_PATCHES_PER_CLUSTER
    import numpy as np
    gap = parse_gap_report(_mk_report(b"ABCD", program=program))
    replay = replay_gaps(program, [gap])
    blame = localize(program, replay.clusters[0])
    patches = enumerate_patches(program, blame)
    assert 0 < len(patches) <= MAX_PATCHES_PER_CLUSTER
    for p in patches:
        patched = apply_patch(program, p)
        before = np.asarray(program.instrs)
        after = np.asarray(patched.instrs)
        diff = np.argwhere((before != after).any(axis=1)).ravel()
        assert list(diff) == [p.pc]          # exactly one row rewritten
        assert patched.n_blocks == program.n_blocks
        assert list(patched.block_ids) == list(program.block_ids)


def test_save_patched_program_roundtrip(tmp_path):
    binding = get_binding("test_safe")
    d = _gap_corpus(tmp_path, binding.program())
    result, patched = run_repair(binding, d)
    out = str(tmp_path / "repaired.npz")
    save_patched_program(patched, out)
    loaded = load_program_file(out)
    assert loaded.name.endswith("+repaired")
    assert loaded.n_blocks == patched.n_blocks
    assert list(loaded.block_ids) == list(patched.block_ids)
    assert verdict_class(concrete_run(loaded, b"ABCD").status) == "ok"


def test_write_repair_ledger_consumes_inputs(tmp_path):
    binding = get_binding("test_safe")
    d = _gap_corpus(tmp_path, binding.program())
    result, _ = run_repair(binding, d)
    assert write_repair_ledger(d, result) == 1
    [rec] = load_ledger(d)
    assert rec["binding"] == "test_safe"
    assert rec["status"] == "repaired" and rec["patch"]
    assert set(rec["consumed"]) == \
        {md5_hex(b) for b in (b"ABCD", b"ABCDxx", b"ABCD\x00\x01")}


def test_install_repaired_refuses_uncertifiable(tmp_path):
    """A 'repaired' program the native tier cannot re-certify is
    refused — install_repaired never grandfathers a patched proxy.
    (Native absent counts as refusal: a skipped check cannot admit a
    program whose whole provenance is changed semantics.)"""
    binding = ProxyBinding(
        name="cert-refuse", proxy_target="test",
        native=NativeSpec(argv=["/nonexistent/definitely-not-built"]),
        benign_seed=b"hello")
    out = str(tmp_path / "p.npz")
    save_patched_program(get_target("test"), out)
    with pytest.raises(CertificationError):
        install_repaired(binding, out)


# -- conformance lint (kb-lint --gaps-dir) -----------------------------


def test_lint_backlog_warning_thresholded(tmp_path):
    program = get_target("test")
    d = _gap_corpus(tmp_path, program)
    assert conformance_lint(d, backlog_threshold=8) == []
    findings = conformance_lint(d, backlog_threshold=0)
    [f] = findings
    assert f.severity == "warning" and f.code == "proxy-gap-backlog"
    assert f.data["unconsumed"] == 3
    assert f.data["binding"] == "test_safe"


def test_lint_backlog_clears_when_ledger_consumes(tmp_path):
    binding = get_binding("test_safe")
    d = _gap_corpus(tmp_path, binding.program())
    result, _ = run_repair(binding, d)
    write_repair_ledger(d, result)
    assert conformance_lint(d, backlog_threshold=0) == []


def test_lint_drift_error_on_regressed_repair(tmp_path):
    binding = get_binding("test_safe")
    program = binding.program()
    d = _gap_corpus(tmp_path, program)
    result, _ = run_repair(binding, d)
    write_repair_ledger(d, result)
    # a NEWER gap on the repaired (binding, edge) site = drift
    GapIndex(d).admit(_mk_report(b"ABCDQQ", t=result["t"] + 1000,
                                 program=program))
    findings = conformance_lint(d, backlog_threshold=99)
    [f] = [x for x in findings if x.code == "conformance-drift"]
    assert f.severity == "error"
    assert f.data["binding"] == "test_safe"
    assert f.data["newer"] == [md5_hex(b"ABCDQQ")]
    # errors sort first for the SARIF/report stream
    assert findings[0].code == "conformance-drift"


def test_lint_tool_sarif_anchors_binding_source_line(tmp_path):
    """Satellite: the SARIF physicalLocation for conformance findings
    must anchor on the BINDING's proxy program source line (the
    registered target builder), not a synthetic URI."""
    from killerbeez_tpu.tools.lint_tool import (
        conformance_reports, sarif_report,
    )
    d = _gap_corpus(tmp_path, get_target("test"))
    reports = conformance_reports(d, threshold=0)
    assert set(reports) == {"conformance:test_safe"}
    rec = reports["conformance:test_safe"]
    assert rec["location"]["uri"].endswith("models/targets.py")
    assert rec["location"]["line"] > 1
    sarif = sarif_report({k: v["report"] for k, v in reports.items()},
                         {k: v["location"] for k, v in reports.items()})
    res = sarif["runs"][0]["results"]
    assert res, "backlog finding must surface in SARIF"
    loc = res[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("models/targets.py")
    assert loc["region"]["startLine"] == rec["location"]["line"]


def test_lint_tool_cli_gaps_dir_lane(tmp_path, capsys):
    from killerbeez_tpu.tools.lint_tool import main as lint_main
    d = _gap_corpus(tmp_path, get_target("test"))
    # warnings alone exit 0; the lane lints ONLY conformance
    rc = lint_main(["--gaps-dir", d, "--gap-backlog", "0", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    # --gaps-dir alone lints ONLY the conformance tier
    assert set(out["targets"]) == {"conformance:test_safe"}
    assert out["warnings"] == 1 and out["errors"] == 0
    codes = [f["code"] for f in
             out["targets"]["conformance:test_safe"]["findings"]]
    assert codes == ["proxy-gap-backlog"]
    # an empty gap dir is a clean bill
    assert lint_main(["--gaps-dir", str(tmp_path / "none"),
                      "--gap-backlog", "0"]) == 0


# -- corpus sidecar: validation.repair bounds --------------------------


def test_store_update_repair_requires_validation_block(tmp_path):
    store = CorpusStore(str(tmp_path))
    e = CorpusEntry(b"GAPPY", sig=[4])
    store.put(e)
    rep = {"verdict": "repaired", "patch": "const-nudge@pc22",
           "reason": None, "t": 5.0}
    # no validation block yet: repair has nothing to attach to
    assert store.update_repair(e.md5, rep) is False
    store.update_validation(e.md5, {"verdict": "proxy_only",
                                    "repro": 0, "repeats": 3})
    assert store.update_repair(e.md5, rep) is True
    got = {x.md5: x for x in store.load()}[e.md5]
    assert got.validation["repair"]["verdict"] == "repaired"
    assert store.update_repair("f" * 32, rep) is False


def _val_row(buf, repair):
    import base64
    from killerbeez_tpu.corpus.store import coverage_hash
    meta = {"sig": [1], "md5": md5_hex(buf),
            "cov_hash": coverage_hash([1], buf), "seq": 0,
            "source": "local", "tier": "native",
            "validation": {"verdict": "proxy_only", "repro": 0,
                           "repeats": 3, "repair": repair}}
    return {"worker": "w", "md5": md5_hex(buf),
            "cov_hash": coverage_hash([1], buf),
            "content_b64": base64.b64encode(buf).decode(),
            "meta": meta}


def test_entry_validator_accepts_bounded_repair():
    entry, reason = EntryValidator().validate(_val_row(
        b"DATA", {"verdict": "unrepairable", "patch": None,
                  "reason": "patch:space-exhausted", "t": 9.0}))
    assert reason is None
    assert entry.validation["repair"]["verdict"] == "unrepairable"


@pytest.mark.parametrize("repair", [
    "repaired",                              # not a dict
    {"verdict": "probably"},                 # unknown verdict
    {"verdict": "repaired", "t": "noon"},    # non-numeric t
    {"verdict": "repaired", "patch": "p" * 257},
    {"verdict": "repaired", "reason": ["x"]},
])
def test_entry_validator_rejects_malformed_repair(repair):
    entry, reason = EntryValidator().validate(_val_row(b"DATA",
                                                       repair))
    assert entry is None and reason == "schema:repair"


# -- the --auto-repair plateau stage -----------------------------------


class _Stats:
    def __init__(self):
        self.new_paths = 0
        self.iterations = 0


class _Telemetry:
    def __init__(self):
        from killerbeez_tpu.telemetry import MetricsRegistry
        self.registry = MetricsRegistry()
        self.events = []

    def event(self, etype, **fields):
        self.events.append({"type": etype, **fields})


class _RepairStubFuzzer:
    PIPELINE_DEPTH = 0

    def __init__(self, out, store=None):
        self.stats = _Stats()
        self.batch_size = 1
        self.output_dir = str(out)
        self.telemetry = _Telemetry()
        self.store = store


class _StubBridge:
    def __init__(self, binding, gaps=0):
        self.binding = binding
        self.proxy_gaps = gaps


def test_proxy_repairer_fires_only_at_plateau_with_new_gaps(tmp_path):
    from killerbeez_tpu.fuzzer.repairer import ProxyRepairer
    binding = get_binding("test_safe")
    _gap_corpus(tmp_path, binding.program(), name="proxy_gaps")
    fz = _RepairStubFuzzer(tmp_path)
    bridge = _StubBridge(binding, gaps=3)
    rep = ProxyRepairer(bridge, plateau_batches=4, apply=False)
    # progress: never fires
    for i in range(10):
        fz.stats.iterations = i
        fz.stats.new_paths = i
        rep.maybe_repair(fz)
    assert rep.attempts == 0
    # plateau, but not past the window yet
    fz.stats.iterations += 3
    rep.maybe_repair(fz)
    assert rep.attempts == 0
    # past the window with accumulated gaps: one attempt
    fz.stats.iterations += 10
    rep.maybe_repair(fz)
    assert rep.attempts == 1 and rep.last_status == "repaired"
    c = fz.telemetry.registry.snapshot()["counters"]
    assert c["repair_attempts"] == 1 and c["repair_repaired"] == 1
    [ev] = [e for e in fz.telemetry.events
            if e["type"] == "proxy_repair"]
    assert ev["status"] == "repaired" and ev["clusters"] == 1
    # same evidence, next plateau: re-arms only when gaps GROW
    fz.stats.iterations += 10
    rep.maybe_repair(fz)
    assert rep.attempts == 1
    bridge.proxy_gaps += 1
    rep.finish(fz)
    assert rep.attempts == 2


def test_proxy_repairer_writes_back_corpus_and_ledger(tmp_path,
                                                      monkeypatch):
    import killerbeez_tpu.hybrid.registry as registry
    from killerbeez_tpu.fuzzer.repairer import ProxyRepairer
    # install is the real-substrate e2e's job; stub it so this unit
    # test neither needs the native toolchain nor touches the registry
    monkeypatch.setattr(registry, "install_repaired",
                        lambda base, path, certify=True: base)
    binding = get_binding("test_safe")
    store = CorpusStore(str(tmp_path / "corpus"))
    e = CorpusEntry(b"ABCD", sig=[2])
    store.put(e)
    store.update_validation(e.md5, {"verdict": "proxy_only",
                                    "repro": 0, "repeats": 3})
    _gap_corpus(tmp_path, binding.program(), name="proxy_gaps")
    fz = _RepairStubFuzzer(tmp_path, store=store)
    rep = ProxyRepairer(_StubBridge(binding, gaps=3), apply=True)
    result = rep.repair(fz)
    assert result["status"] == "repaired"
    # ledger landed (the lint's consumed-set)...
    gaps_dir = str(tmp_path / "proxy_gaps")
    assert load_ledger(gaps_dir)
    # ...and the corpus entry's sidecar carries the repair verdict
    got = {x.md5: x for x in store.load()}[e.md5]
    assert got.validation["repair"]["verdict"] == "repaired"
    assert got.validation["repair"]["patch"]


def test_fuzzer_loop_wires_repairer_hooks():
    """The loop drives repairer.maybe_repair at batch end and
    repairer.finish after the bridge drains — presence pins."""
    import inspect
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    src = inspect.getsource(Fuzzer)
    assert "self.repairer.maybe_repair(self)" in src
    assert "self.repairer.finish(self)" in src


def test_cli_auto_repair_requires_hybrid(tmp_path, capsys):
    from killerbeez_tpu.fuzzer.cli import main as cli_main
    seed = tmp_path / "seed"
    seed.write_bytes(b"AAAA")
    rc = cli_main(["file", "jit_harness", "havoc",
                   "-i", '{"target": "test"}', "-sf", str(seed),
                   "-o", str(tmp_path / "out"), "-n", "16",
                   "-b", "16", "--auto-repair"])
    err = capsys.readouterr().err
    assert rc == 2 and "--hybrid" in err


# -- kb-repair CLI + native e2e (corpus_bin) ---------------------------


def test_repair_tool_unknown_binding_exits_2(tmp_path, capsys):
    from killerbeez_tpu.tools.repair_tool import main as repair_main
    rc = repair_main(["--binding", "no-such", "--gaps-dir",
                      str(tmp_path)])
    assert rc == 2


def test_repair_tool_require_repaired_gate(tmp_path, capsys):
    from killerbeez_tpu.tools.repair_tool import main as repair_main
    binding = get_binding("test_safe")
    d = _gap_corpus(tmp_path, binding.program())
    assert repair_main(["--binding", "test_safe", "--gaps-dir", d,
                        "--require-repaired", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["status"] == "repaired"
    assert out["clusters"][0]["blame"]["pc"] == \
        _d_check(binding.program()).pc
    # empty dir: no-gaps fails the gate
    assert repair_main(["--binding", "test_safe", "--gaps-dir",
                        str(tmp_path / "empty"),
                        "--require-repaired"]) == 1


def test_repair_tool_probe_and_apply_e2e(tmp_path, capsys,
                                         corpus_bin):
    """The acceptance e2e on the REAL pair: --probe mints the gap
    corpus from both tiers, repair localizes the differing guard,
    --apply installs the re-certified <binding>+repaired binding."""
    from killerbeez_tpu.hybrid.registry import _BINDINGS
    from killerbeez_tpu.tools.repair_tool import main as repair_main
    d = str(tmp_path / "gaps")
    rc = repair_main(["--binding", "test_safe", "--gaps-dir", d,
                      "--probe", "--apply", "--require-repaired",
                      "--json"])
    out = json.loads(capsys.readouterr().out)
    try:
        assert rc == 0, out
        assert out["status"] == "repaired"
        binding = get_binding("test_safe")
        want = _d_check(get_target("test"))
        assert any(c["blame"]["pc"] == want.pc
                   for c in out["clusters"])
        assert out["installed"] == "test_safe+repaired"
        installed = get_binding("test_safe+repaired")
        prog = installed.program()
        assert prog.name.endswith("+repaired")
        # the installed proxy agrees with hybrid-safe on the old gap
        assert verdict_class(concrete_run(prog, b"ABCD").status) \
            == "ok"
        # drift lint is clean right after the repair
        assert conformance_lint(d, backlog_threshold=0) == []
    finally:
        _BINDINGS.pop("test_safe+repaired", None)


def test_repair_tool_probe_faithful_binding_finds_nothing(
        tmp_path, capsys, corpus_bin):
    """The faithful test⇄test-plain pair probes clean: no gap
    reports, verdict no-gaps, exit 0 (without --require-repaired)."""
    from killerbeez_tpu.tools.repair_tool import main as repair_main
    d = str(tmp_path / "gaps")
    rc = repair_main(["--binding", "test", "--gaps-dir", d,
                      "--probe", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["status"] == "no-gaps"
    assert out["reason"] == "gap:none-for-binding"
