"""Mesh-resident generations (ISSUE 10): the device generation scan
(ops/generations.py) lifted into a shard_map over the (dp, mp) mesh —
per-dp-shard virgin maps, seed-slot rings and findings rings, with
in-scan ICI AND-folds on the gen_fold_every cadence.

Pins the ISSUE 10 contracts on the virtual 8-device CPU mesh:
  * dp>1 parity — with feedback off the mesh-generations candidate
    stream is bit-identical to the host-driven mesh loop (findings,
    folded virgin maps AND corpus-store write-through), the mesh twin
    of the PR 9 single-chip parity gate, and a sparser fold cadence
    over-reports but never under-reports (folded maps identical);
  * --generations no longer stands down under --mesh;
  * generation-tail edge cases — pow2 quantization of G when -n does
    not fill G*b (exec totals stay exact, watchdog scales per
    dispatch), findings-ring wrap exactly at capacity (cap == raw is
    lossless, cap == raw-1 drops exactly the excess into the
    findings_ring_drops counter — never silent);
  * ledger-replay determinism at dp>1 — identical runs produce
    identical findings and identical shard-ordered ring_admit
    streams, independent of drain interleaving;
  * kb-timeline reports per-shard generation occupancy for a dp>1
    campaign (the ROADMAP item 1 acceptance artifact at mesh scale).
"""

import json
import os

import numpy as np
import pytest

from killerbeez_tpu.fuzzer.loop import Fuzzer
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.mutators.factory import mutator_factory
from killerbeez_tpu.parallel import ShardedCampaignDriver

SEED = b"CG\x02\x04\x05\x41xx"
MESH = "4,2"
B = 64                                  # 16 lanes/chip on dp=4


def _findings(root):
    out = {}
    for kind in ("crashes", "hangs", "new_paths"):
        d = os.path.join(root, kind)
        out[kind] = sorted(
            f for f in (os.listdir(d) if os.path.isdir(d) else [])
            if len(f) == 32)
    return out


def _mesh_driver(iopts=None, mopts='{"seed": 11}', batch=B):
    instr = instrumentation_factory(
        "jit_harness", iopts or '{"target": "cgc_like"}')
    mut = mutator_factory("havoc", mopts, SEED)
    return ShardedCampaignDriver(MESH, instr, mut,
                                 batch_size=batch), instr


# ---------------------------------------------------------------------------
# dp>1 parity: mesh-generations == host-driven mesh loop (fb off)
# ---------------------------------------------------------------------------


def test_mesh_generations_matches_host_mesh_loop(tmp_path):
    """THE ISSUE 10 parity contract, end to end through the CLI:
    with feedback off the dp>1 mesh-generations candidate stream is
    bit-identical to the host-driven mesh loop — findings, folded
    virgin maps, AND the corpus-store write-through — and the mode
    no longer warns a mesh stand-down."""
    from killerbeez_tpu.fuzzer.cli import main as cli_main

    seed_file = tmp_path / "seed"
    seed_file.write_bytes(SEED)

    def run(name, extra):
        out = tmp_path / name
        st = tmp_path / f"{name}.json"
        rc = cli_main([
            "file", "jit_harness", "havoc", "--mesh", MESH,
            "-i", '{"target": "cgc_like"}', "-m", '{"seed": 11}',
            "-sf", str(seed_file), "-o", str(out),
            "-b", str(B), "-n", str(8 * B), "-fb", "0",
            "--corpus-dir", str(out / "corpus"),
            "-isd", str(st), *extra])
        assert rc == 0
        store = sorted(f for f in os.listdir(out / "corpus")
                       if len(f) == 32)
        return _findings(str(out)), json.loads(st.read_text()), store

    fh, sh, ch = run("host", [])
    fg, sg, cg = run("gen", ["-G", "4"])
    assert sh["total_execs"] == sg["total_execs"] == 8 * B
    assert any(fh.values()), "control found nothing to compare"
    assert fg == fh
    assert cg == ch and ch, "store write-through diverged"
    for k in ("virgin_bits", "virgin_crash", "virgin_tmout"):
        assert sg[k] == sh[k], f"{k} diverged"


def test_mesh_fold_cadence_over_reports_never_under_reports():
    """gen_fold_every trades ICI fold traffic against duplicate
    re-finds, never against findings: between folds shards may
    re-find each other's paths (fold_every g >= fold_every 1 lanes,
    and every fold-1 finding is in the fold-g rings), and the FOLDED
    virgin maps end byte-identical regardless of cadence — the same
    doctrine the per-batch step's per-dp-shard dedup pins."""
    outs = {}
    for fe in (1, 4):
        drv, instr = _mesh_driver(
            iopts=json.dumps({"target": "cgc_like",
                              "gen_fold_every": fe}))
        assert drv.supports_batch_generations()
        h = drv.test_batch_generations(B, 4, reseed=False)
        outs[fe] = (h.materialize(),
                    np.asarray(drv.state.virgin_bits),
                    np.asarray(drv.state.virgin_crash))

    def ring_bufs(h):
        out = set()
        for d in range(h.n_shards):
            s = h.shard(d)
            for i in range(min(int(s.fr_ptr), int(s.cap))):
                out.add(bytes(s.fr_bufs[i, :int(s.fr_len[i])]))
        return out

    a, b = outs[1], outs[4]
    assert int(b[0].fr_ptr.sum()) >= int(a[0].fr_ptr.sum())
    assert ring_bufs(a[0]) <= ring_bufs(b[0])   # never under-report
    np.testing.assert_array_equal(a[1], b[1])   # folded maps agree
    np.testing.assert_array_equal(a[2], b[2])
    # and the returned maps are dp-replicated (a dispatch always
    # ends on a fold): per-shard novelty already merged
    assert a[0].n_shards == 4


# ---------------------------------------------------------------------------
# generation-tail edge cases
# ---------------------------------------------------------------------------


def test_mesh_tail_quantizes_to_pow2_and_execs_exact(tmp_path):
    """-n not filling G*b at mesh scale: tail dispatches quantize G
    down to a power of two (g is a STATIC jit argument — an
    arbitrary tail would recompile the whole sharded scan), the exec
    total stays exact, the watchdog arms per-dispatch scales, and
    the mode never stood down."""
    from tests.test_generations import _RecordingWatchdog

    wd = _RecordingWatchdog()
    drv, _ = _mesh_driver()
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=B,
                feedback=0, generations=8, watchdog=wd)
    try:
        fz.run(B * 11)      # 8 + (3 -> 2) + 1 generations
    finally:
        wd.stop()
    assert fz.stats.iterations == B * 11
    assert not fz._gen_warned, "mesh stood --generations down"
    assert all(k & (k - 1) == 0 for k in wd.scales), wd.scales
    assert wd.scales[:2] == [8, 2]


def test_mesh_findings_ring_wrap_exactly_at_capacity(tmp_path):
    """Findings-ring wrap at the exact boundary, per shard: cap ==
    the busiest shard's raw interesting count is lossless (ring
    exactly full, zero drops); cap == raw-1 drops EXACTLY the excess
    and lands it in the findings_ring_drops counter — overflow is
    counted, never silent."""
    # probe the deterministic raw per-shard counts (reseed off)
    drv, _ = _mesh_driver()
    h = drv.test_batch_generations(B, 4, reseed=False).materialize()
    raw = [int(p) for p in h.fr_ptr]
    top = max(raw)
    assert top >= 2, "cgc_like found too little to exercise the ring"

    def run_with_cap(name, cap):
        drv, _ = _mesh_driver(
            iopts=json.dumps({"target": "cgc_like",
                              "gen_findings_cap": cap}))
        fz = Fuzzer(drv, output_dir=str(tmp_path / name),
                    batch_size=B, feedback=0, generations=4)
        fz.run(4 * B)       # exactly one dispatch
        return fz

    fz = run_with_cap("exact", top)
    assert fz.telemetry.registry.counters.get(
        "findings_ring_drops", 0) == 0
    fz = run_with_cap("minus1", top - 1)
    want = sum(r - min(r, top - 1) for r in raw)
    assert fz.telemetry.registry.counters.get(
        "findings_ring_drops", 0) == want
    # the drop under-reports findings relative to the lossless run
    assert len(_findings(str(tmp_path / "minus1"))["new_paths"]) \
        <= len(_findings(str(tmp_path / "exact"))["new_paths"])


# ---------------------------------------------------------------------------
# ledger replay at dp>1 (feedback on)
# ---------------------------------------------------------------------------


def test_mesh_ledger_replay_deterministic_per_shard(tmp_path):
    """Feedback ON at dp>1: device ring admissions replay through
    per-shard (shard, slot)-keyed mirrors in shard order, so two
    identical campaigns produce the same findings set AND the same
    shard-ordered ring_admit stream — the replay is independent of
    drain interleaving.  Every admission lands as a real corpus-store
    entry and arms stay duplicate-free."""
    def run(name):
        drv, _ = _mesh_driver(batch=256)
        fz = Fuzzer(drv, output_dir=str(tmp_path / name),
                    batch_size=256, feedback=8, generations=4,
                    corpus_dir=str(tmp_path / name / "corpus"))
        fz.run(2048)
        evs = [json.loads(l) for l in
               open(tmp_path / name / "events.jsonl") if l.strip()]
        admits = [(e["shard"], e["slot"], e["gen"], e["md5"],
                   e["parent"])
                  for e in evs if e["type"] == "ring_admit"]
        return fz, admits

    fz1, admits1 = run("a")
    fz2, admits2 = run("b")
    assert admits1, "device rings never admitted on cgc_like"
    assert admits1 == admits2
    assert _findings(str(tmp_path / "a")) == \
        _findings(str(tmp_path / "b"))
    assert {s for s, *_ in admits1} == {0, 1, 2, 3}, \
        "not every dp shard admitted"
    for _, slot, _, md5, _ in admits1:
        assert slot >= 1                    # slot 0 stays pinned
        assert (tmp_path / "a" / "corpus" / md5).exists()
    md5s = [getattr(a, "md5", None) for a in fz1.scheduler.arms]
    assert len(md5s) == len(set(md5s))


def test_dp1_mesh_generations_drains_through_shard_view(tmp_path):
    """REGRESSION: a dp=1 mesh outcome still carries the leading dp
    axis on every ring/ledger field — the drain must go through the
    shard(0) view, not treat it as a single-chip outcome (which
    indexed the dp axis and crashed on the first interesting
    lane)."""
    instr = instrumentation_factory("jit_harness",
                                    '{"target": "cgc_like"}')
    mut = mutator_factory("havoc", '{"seed": 11}', SEED)
    drv = ShardedCampaignDriver("1,2", instr, mut, batch_size=B)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=B,
                feedback=8, generations=4,
                corpus_dir=str(tmp_path / "o" / "corpus"))
    fz.run(8 * B)
    assert fz.stats.iterations == 8 * B
    assert not fz._gen_warned
    assert fz.stats.new_paths > 0, "nothing drained — vacuous"


# ---------------------------------------------------------------------------
# kb-timeline: per-shard occupancy (acceptance artifact at mesh scale)
# ---------------------------------------------------------------------------


def test_timeline_reports_per_shard_generation_occupancy(tmp_path):
    """A dp>1 --generations --trace campaign yields a kb-timeline
    generations section with one row per dp shard (dispatch and
    generation totals + occupancy over the generation window) and a
    device-bound verdict — ROADMAP item 1's acceptance artifact, now
    at mesh scale."""
    from killerbeez_tpu.tools.timeline_tool import build_report

    drv, _ = _mesh_driver(batch=256)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=256,
                feedback=0, generations=4, trace=65536)
    fz.run(4096)
    doc = json.load(open(tmp_path / "o" / "trace.json"))
    report = build_report(doc, None, None)
    gr = report.get("generations")
    assert gr and gr["dispatches"] >= 2
    assert gr["n_shards"] == 4
    assert set(gr["shards"]) == {"0", "1", "2", "3"}
    for sd in gr["shards"].values():
        assert sd["dispatches"] == gr["dispatches"]
        assert sd["generations_total"] == gr["generations_total"]
        assert sd["occupancy"] > 0.5
    assert gr["device_bound"], (
        f"host stages on the critical path: device "
        f"{gr['device_occupancy']:.1%} vs host "
        f"{gr['host_occupancy']:.1%}")
