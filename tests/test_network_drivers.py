"""network_server / network_client driver tests against the corpus
network fixtures (reference test-fuzzer.sh network scenarios,
SURVEY §4): crash on the magic packet sequence, clean run otherwise,
multipart mutation via the manager mutator, and the listen-probe that
must not consume the target's accept().
"""

import json

import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_NONE
from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.drivers.network_server import is_port_listening
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.mutators.factory import mutator_factory
from killerbeez_tpu.utils.serialization import encode_mem_array

PORT = 7741  # unique-ish per test file to avoid TIME_WAIT collisions


def seq(*parts: bytes) -> bytes:
    return encode_mem_array(list(parts)).encode()


def make_server(corpus_bin, port, mutator=None, udp=False,
                instr_name="afl"):
    instr = instrumentation_factory(instr_name, None)
    args = f"{port} udp" if udp else str(port)
    drv = driver_factory("network_server", json.dumps(
        {"path": corpus_bin("network_server"), "arguments": args,
         "port": port, "udp": int(udp), "timeout": 1.0}), instr, mutator)
    return drv, instr


def test_server_crash_sequence(corpus_bin):
    drv, instr = make_server(corpus_bin, PORT)
    assert drv.test_input(seq(b"HELO", b"BOOM")) == FUZZ_CRASH
    assert instr.last_unique_crash()
    assert drv.test_input(seq(b"HELO", b"nope")) == FUZZ_NONE
    # crash repeats deterministically
    assert drv.test_input(seq(b"HELO", b"BOOM")) == FUZZ_CRASH
    drv.cleanup()
    instr.cleanup()


def test_server_coverage_novelty(corpus_bin):
    drv, instr = make_server(corpus_bin, PORT + 1)
    drv.test_input(seq(b"xxxx"))
    first = instr.is_new_path()
    drv.test_input(seq(b"xxxx"))
    assert first > 0 and instr.is_new_path() == 0
    # reaching the HELO state machine branch is a new path
    drv.test_input(seq(b"HELO", b"yyyy"))
    assert instr.is_new_path() > 0
    drv.cleanup()
    instr.cleanup()


def test_server_multipart_manager_mutator(corpus_bin):
    # part 2 seed "BOOL" is one bit from "BOOM": deterministic
    # bit_flip must reach the crash within its 32 flips
    mut = mutator_factory(
        "manager",
        json.dumps({"mutators": ["nop", "bit_flip"]}),
        seq(b"HELO", b"BOOL"))
    drv, instr = make_server(corpus_bin, PORT + 2, mutator=mut)
    assert drv.num_inputs == 2
    results = []
    for _ in range(64):
        r = drv.test_next_input()
        if r is None:
            break
        results.append(r)
    assert results  # ran mutated multi-packet sequences
    assert FUZZ_CRASH in results
    drv.cleanup()
    instr.cleanup()
    mut.cleanup()


def test_server_udp(corpus_bin):
    drv, instr = make_server(corpus_bin, PORT + 3, udp=True)
    assert drv.test_input(seq(b"HELO")) == FUZZ_NONE
    drv.cleanup()
    instr.cleanup()


def test_server_return_code_instr(corpus_bin):
    drv, instr = make_server(corpus_bin, PORT + 4,
                             instr_name="return_code")
    assert drv.test_input(seq(b"HELO", b"BOOM")) == FUZZ_CRASH
    assert drv.test_input(seq(b"HELO", b"okay")) == FUZZ_NONE
    drv.cleanup()
    instr.cleanup()


def test_client_driver(corpus_bin):
    instr = instrumentation_factory("afl", None)
    port = PORT + 5
    drv = driver_factory("network_client", json.dumps(
        {"path": corpus_bin("network_client"), "arguments": str(port),
         "port": port, "timeout": 1.0}), instr, None)
    assert drv.test_input(b"KILL") == FUZZ_CRASH
    assert instr.last_unique_crash()
    assert drv.test_input(b"okay") == FUZZ_NONE
    drv.cleanup()
    instr.cleanup()


def test_is_port_listening_does_not_consume_accept(corpus_bin):
    import socket
    import threading

    port = PORT + 6
    accepted = []
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(1)

    def acceptor():
        srv.settimeout(2.0)
        try:
            conn, _ = srv.accept()
            accepted.append(conn)
        except OSError:
            accepted.append(None)

    th = threading.Thread(target=acceptor)
    th.start()
    assert is_port_listening(port)
    # the probe must NOT have satisfied the accept
    with socket.create_connection(("127.0.0.1", port), timeout=1.0):
        th.join()
    assert accepted and accepted[0] is not None
    accepted[0].close()
    srv.close()
    assert not is_port_listening(port + 1)


def test_single_input_mutator_on_network_driver(corpus_bin):
    """A plain (single-part) mutator is allowed: one packet per exec."""
    mut = mutator_factory("bit_flip", None, b"HELO")
    drv, instr = make_server(corpus_bin, PORT + 7, mutator=mut)
    r = drv.test_next_input()
    assert r in (FUZZ_NONE, FUZZ_CRASH)
    drv.cleanup()
    instr.cleanup()


def test_server_multipart_batched(corpus_bin, tmp_path):
    """VERDICT 'Batched multipart': the manager mutator's batched path
    drives the network driver through the full Fuzzer loop — batch
    generation on-device, per-connection delivery — and still finds
    the multi-packet crash."""
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    mut = mutator_factory(
        "manager",
        json.dumps({"mutators": ["nop", "bit_flip"]}),
        seq(b"HELO", b"BOOL"))
    drv, instr = make_server(corpus_bin, PORT + 7, mutator=mut)
    assert drv.supports_batch
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"),
                batch_size=16, write_findings=False)
    stats = fz.run(96)
    assert stats.crashes >= 1
    assert stats.new_paths > 0
    drv.cleanup()
    instr.cleanup()
    mut.cleanup()


def test_manager_mutate_batch_matches_sequential(corpus_bin):
    """mutate_batch_parts must replay exactly the sequential mutate()
    round-robin (candidate-for-candidate)."""
    opts = json.dumps({"mutators": ["bit_flip", "bit_flip"]})
    seed = seq(b"AB", b"CD")
    seq_mut = mutator_factory("manager", opts, seed)
    bat_mut = mutator_factory("manager", opts, seed)
    sequential = []
    for _ in range(12):
        whole = seq_mut.mutate()
        sequential.append(whole)
    batched = [b"".join(p) for p in bat_mut.mutate_batch_parts(12)]
    assert sequential == batched
