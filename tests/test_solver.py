"""Solver-guided branch cracking: the path-condition solver
(analysis/solver.py), its concrete reference interpreter, the
plateau crack stage (fuzzer/crack.py), solver-cache persistence,
the kb-solve CLI and the kb-stats solver row."""

import json

import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_HANG, FUZZ_NONE
from killerbeez_tpu.analysis.solver import (
    concrete_run, edge_dep_mask, solve_edge, solve_edges,
)
from killerbeez_tpu.models import targets, targets_cgc
from killerbeez_tpu.models.compiler import Assembler
from killerbeez_tpu.tools.solve_tool import main as solve_main

STATUS_NAME = {FUZZ_NONE: "none", FUZZ_CRASH: "crash",
               FUZZ_HANG: "hang"}


# -- concrete reference interpreter ----------------------------------

def _engine_run(prog, data):
    """Ground truth: the batched one-hot engine on one lane."""
    import jax.numpy as jnp
    from killerbeez_tpu import FUZZ_RUNNING
    from killerbeez_tpu.models.vm import run_batch
    L = max(8, len(data))
    buf = np.zeros((1, L), np.uint8)
    buf[0, :len(data)] = np.frombuffer(data, np.uint8)
    res = run_batch(prog, jnp.asarray(buf),
                    jnp.asarray([len(data)], jnp.int32),
                    record_stream=False)
    status = int(res.status[0])
    if status == FUZZ_RUNNING:
        status = FUZZ_HANG
    counts = np.asarray(res.counts)[0][:-1]   # drop overflow column
    hit = {(int(prog.edge_from[i]), int(prog.edge_to[i]))
           for i in np.flatnonzero(counts)}
    return status, int(res.steps[0]), hit


@pytest.mark.parametrize("name", ["test", "hang", "libtest",
                                  "cgc_like"])
def test_concrete_run_matches_engine_builtin(name):
    prog = targets.get_target(name)
    for data in (b"", b"A", b"ABCD", b"H", b"LX", b"CG\x02\x04\xff\x01",
                 b"CG\x01\x03abc", b"\xff" * 12):
        st, steps, hit = _engine_run(prog, data)
        tr = concrete_run(prog, data)
        assert tr.status == st, (name, data)
        assert tr.steps == steps, (name, data)
        assert set(tr.edges) == hit, (name, data)


@pytest.mark.parametrize("name", sorted(targets_cgc.VM_SEEDS))
def test_concrete_run_matches_engine_cgc(name):
    prog = targets.get_target(name)
    seed_fn, crash_fn = targets_cgc.VM_SEEDS[name]
    for data in (seed_fn(), crash_fn()):
        st, steps, hit = _engine_run(prog, data)
        tr = concrete_run(prog, data)
        assert tr.status == st, (name, data)
        assert tr.steps == steps, (name, data)
        assert set(tr.edges) == hit, (name, data)


# -- the edge solver --------------------------------------------------

def test_solver_cracks_every_toy_edge():
    """Acceptance: on the built-in magic-byte targets the solver
    cracks 100% of the static universe, and every emitted input is
    PROVEN (traverses the edge in a concrete run)."""
    for name in ("test", "hang", "libtest", "cgc_like"):
        prog = targets.get_target(name)
        res = solve_edges(prog)
        for edge, r in res.items():
            assert r.status == "solved", (name, edge, r.reason)
            assert edge in concrete_run(prog, r.input).edges, \
                (name, edge)


def test_solver_expect_byte_chain_exact():
    """expect_byte chains solve EXACTLY: the deep `test` edge comes
    back as the literal magic, and each CGC target's magic prefix
    falls out of its chain edges byte for byte."""
    r = solve_edge(targets.get_target("test"), (4, 5))
    assert r.status == "solved" and r.input == b"ABCD"
    for name, magic in (("tlvstack_vm", b"STK1"),
                        ("imgparse_vm", b"QIMG"),
                        ("rledec_vm", b"RLE2")):
        prog = targets.get_target(name)
        # blocks 2..5 are the per-byte match blocks of the chain
        for k in range(4):
            r = solve_edge(prog, (k + 1, k + 2))
            assert r.status == "solved", (name, k, r.reason)
            assert r.input[:k + 1] == magic[:k + 1], (name, k)


def test_solver_unsat_tiers():
    # outside the static universe: immediate unsat
    r = solve_edge(targets.get_target("test"), (0, 5))
    assert r.status == "unsat" and "universe" in r.reason
    # a constant-folded dead branch with NO input reads anywhere on
    # its paths: exhaustively refuted -> honest unsat
    a = Assembler("dead", max_steps=32)
    a.block()
    a.ldi(1, 3)
    a.ldi(2, 5)
    a.br("lt", 1, 2, "out")             # 3 < 5: always taken
    a.block()                           # statically dead
    a.label("out")
    a.block()
    a.halt(0)
    prog = a.build()
    r = solve_edge(prog, (0, 1))
    assert r.status == "unsat" and "refuted" in r.reason
    # ...while the live edge still solves
    assert solve_edge(prog, (0, 2)).status == "solved"


def test_solver_budget_and_loop_honesty():
    # budget exhaustion reports unknown, never a guess
    r = solve_edge(targets_cgc.tlvstack_vm(), (5, 6), budget=5)
    assert r.status == "unknown" and "budget" in r.reason
    # loop-carried state beyond max_visits passes: honest unknown
    a = Assembler("count3", max_steps=64)
    a.block()
    a.ldi(1, 0)
    a.label("loop")
    a.block()
    a.addi(1, 1, 1)
    a.ldi(2, 3)
    a.br("lt", 1, 2, "loop")            # three passes to fall through
    a.block()
    a.halt(0)
    prog = a.build()
    r = solve_edge(prog, (1, 2))
    assert r.status == "unknown"
    # with the visit cap raised the same edge solves
    r = solve_edge(prog, (1, 2), max_visits=4)
    assert r.status == "solved"
    assert (1, 2) in concrete_run(prog, r.input).edges


def test_solver_len_cap_degrades_unsat_to_unknown():
    """Regression: an edge only reachable with inputs LONGER than
    max_len must read unknown (the length domain is clipped — an
    under-approximation), never 'exhaustively refuted'."""
    a = Assembler("longlen", max_steps=16)
    a.block()
    a.load_len(1)
    a.ldi(2, 100)
    a.br("ge", 1, 2, "big")
    a.block()
    a.halt(0)
    a.label("big")
    a.block()
    a.halt(0)
    prog = a.build()
    r = solve_edge(prog, (0, 2), max_len=64)
    assert r.status == "unknown" and "length capped" in r.reason
    # with the cap raised the edge solves and verifies
    r = solve_edge(prog, (0, 2), max_len=128)
    assert r.status == "solved" and len(r.input) >= 100
    assert (0, 2) in concrete_run(prog, r.input).edges


def test_solver_cracks_memory_gated_dispatch():
    """tlvstack's PRIV tier needs the KEY unlock to set a privilege
    flag in VM memory first — the solver's concrete memory tracking
    plus one loop revisit cracks the whole two-command sequence."""
    prog = targets_cgc.tlvstack_vm()
    df_edges = list(zip(np.asarray(prog.edge_from).tolist(),
                        np.asarray(prog.edge_to).tolist()))
    # pick the deepest edge of the seed's PRIV walk (the flag-gated
    # dispatch tree) and re-solve it from scratch
    tr = concrete_run(prog, targets_cgc.tlvstack_vm_seed())
    deep = tr.edges[-3]
    assert deep in df_edges
    r = solve_edge(prog, deep)
    assert r.status == "solved", r.reason
    vtr = concrete_run(prog, r.input)
    assert deep in vtr.edges
    assert b"KBVMLOCK" in r.input       # the unlock keyword was forced


def test_solver_never_emits_unverified():
    """Every solved result across a full CGC sweep re-verifies; every
    non-solved result carries a reason and no input."""
    prog = targets_cgc.rledec_vm()
    res = solve_edges(prog)
    solved = [r for r in res.values() if r.status == "solved"]
    assert len(solved) >= 50            # CI floor, see workflow
    for r in res.values():
        if r.status == "solved":
            assert r.edge in concrete_run(prog, r.input).edges
        else:
            assert r.input is None and r.reason


# -- focused-mutation masks ------------------------------------------

def test_edge_dep_mask_from_frontier():
    prog = targets.get_target("test")
    # frontier = the deep expect_byte edges: deps are bytes 0..3
    mask = edge_dep_mask(prog, [(2, 3), (3, 4), (4, 5)])
    assert mask is not None and set(mask) <= {0, 1, 2, 3}
    assert 3 in mask                    # the deepest byte is present
    # no edges -> no mask
    assert edge_dep_mask(prog, []) is None


# -- the crack stage e2e ----------------------------------------------

def _crack_campaign(tmp_path, target, plateau=1, batch=64,
                    n_batches=70, store=True):
    """A blind-seed campaign sized so the plateau window — padded by
    the loop's PIPELINE_DEPTH, since triage lags dispatch — trips
    well before the exec budget runs out."""
    import shutil
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory
    instr = instrumentation_factory(
        "jit_harness", json.dumps({"target": target,
                                   "novelty": "throughput"}))
    mut = mutator_factory("havoc", '{"seed": 11}', b"\x00" * 8)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "out"),
                batch_size=batch, write_findings=False,
                corpus_dir=str(tmp_path / "corpus") if store else None)
    fz.cracker = BranchCracker(instr.program,
                               plateau_batches=plateau,
                               store=fz.store)
    fz.run(batch * n_batches)
    return fz, instr, mut


def test_crack_reaches_full_static_coverage(tmp_path):
    """Acceptance: a plateau-crack campaign from a BLIND seed reaches
    100% of the statically-reachable edges of the magic-byte target —
    havoc alone essentially never guesses 'ABCD' in 30 tiny batches —
    and the solved crasher input finds the planted bug."""
    fz, instr, mut = _crack_campaign(tmp_path, "test")
    prog = instr.program
    vb = np.asarray(instr.virgin_bits)
    covered = set(np.flatnonzero(vb != 0xFF).tolist())
    goal = {int(s) for s in np.asarray(prog.edge_slot)}
    assert goal <= covered
    reg = fz.telemetry.registry
    assert reg.counters.get("solver_solved", 0) > 0
    assert reg.counters.get("solver_injected", 0) > 0
    assert fz.stats.crashes >= 1        # the ABCD wild-pointer write
    # frontier emptied: the focus mask cleared again
    assert mut.focus_positions is None
    assert reg.gauges.get("solver_frontier") == 0


def test_crack_cache_persists_and_resumes(tmp_path):
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    fz, instr, _ = _crack_campaign(tmp_path, "test")
    # loop-attached crackers persist through the unified checkpoint
    # epoch (resilience/checkpoint.py) — verdicts and campaign state
    # land in ONE atomic write, so a kill between them cannot forget
    # crack verdicts the corpus already reflects
    assert (tmp_path / "corpus" / "checkpoint.json").exists()
    ck = json.loads((tmp_path / "corpus" / "checkpoint.json")
                    .read_text())
    cache = ck["solver"]
    assert any(v.get("status") == "solved" for v in cache.values())
    # a fresh cracker over the same store starts warm: no re-solving
    c2 = BranchCracker(instr.program, store=fz.store)
    assert c2.cache == cache


def test_crack_installs_focus_mask_on_unsolvable_frontier(tmp_path):
    """When edges stay uncovered (here: artificially marked unknown),
    the cracker feeds the mutators an Angora-style byte mask from the
    frontier's dependency sets."""
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    fz, instr, mut = _crack_campaign(tmp_path, "test", n_batches=4,
                                     store=False)
    cracker = fz.cracker
    # pretend every edge is unsolvable so injection can't cover them
    for e in cracker.edges:
        cracker.cache[cracker._key(e)] = {"status": "unknown",
                                          "reason": "test"}
    # wipe coverage so a frontier exists
    import jax.numpy as jnp
    instr.virgin_bits = jnp.full_like(instr.virgin_bits, 0xFF)
    cracker.crack(fz)
    assert mut.focus_positions is not None
    assert set(mut.focus_positions.tolist()) <= {0, 1, 2, 3}
    # fused paths stand down while the mask is installed
    assert not instr.wants_fused(mut)
    mut.set_focus_mask(None)


# -- kb-solve CLI -----------------------------------------------------

def test_kb_solve_cli_json(capsys):
    assert solve_main(["test", "--json", "--explain"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["solved"] == len(rep["edges"])
    deep = rep["edges"]["4:5"]
    assert bytes.fromhex(deep["input_hex"]) == b"ABCD"
    assert any("input[3]" in c for c in deep["conditions"])


def test_kb_solve_cli_edge_and_block(capsys):
    assert solve_main(["test", "--edge", "4:5"]) == 0
    out = capsys.readouterr().out
    assert "4:5: solved" in out and "ABCD" in out
    assert solve_main(["test", "--block", "5", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert list(rep["edges"]) == ["4:5"]


def test_kb_solve_cli_require_solved_gate(capsys):
    assert solve_main(["test", "--require-solved", "11"]) == 0
    capsys.readouterr()
    assert solve_main(["test", "--require-solved", "12"]) == 1
    assert "FAIL" in capsys.readouterr().err
    assert solve_main(["no_such_target"]) == 2


# -- kb-stats solver row ----------------------------------------------

def test_stats_tui_solver_row():
    from killerbeez_tpu.telemetry import MetricsRegistry
    from killerbeez_tpu.tools.stats_tui import render
    reg = MetricsRegistry()
    reg.count("execs", 100)
    frame = render(reg.snapshot())
    assert "solver" not in frame        # row hidden until it matters
    reg.count("solver_attempts", 9)
    reg.count("solver_solved", 7)
    reg.count("solver_unsat", 1)
    reg.count("solver_unknown", 1)
    reg.count("solver_injected", 7)
    reg.gauge("solver_frontier", 2)
    frame = render(reg.snapshot())
    assert "solver" in frame
    assert "7 solved" in frame and "1 unsat" in frame
    assert "2 frontier pending" in frame and "7 injected" in frame
