"""afl instrumentation tests: forkserver + SHM bitmap + virgin-map
novelty on real host binaries, through the instrumentation vtable and
the full fuzzer loop (reference smoke_test.sh behavioral gates,
SURVEY §4: exact new-path counts on the fixture, crash found from the
one-bit-away seed, state round-trip and merge).
"""

import json
import os

import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_NONE
from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.fuzzer.loop import Fuzzer
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.mutators.factory import mutator_factory


def make_stack(corpus_bin, mutator="bit_flip", seed=b"ABC@",
               instr_opts=None, driver="stdin", mut_opts=None):
    instr = instrumentation_factory("afl", json.dumps(instr_opts or {}))
    mut = mutator_factory(mutator, mut_opts, seed)
    dopts = {"path": corpus_bin("test")}
    if driver == "file":
        dopts["arguments"] = "@@"
    drv = driver_factory(driver, json.dumps(dopts), instr, mut)
    return drv, instr, mut


def test_single_exec_crash_and_novelty(corpus_bin):
    drv, instr, _ = make_stack(corpus_bin)
    # first exec of any input is a new path on a fresh virgin map
    assert drv.test_input(b"zzzz") == FUZZ_NONE
    assert instr.is_new_path() > 0
    assert drv.test_input(b"zzzz") == FUZZ_NONE
    assert instr.is_new_path() == 0  # same path twice
    assert drv.test_input(b"ABCD") == FUZZ_CRASH
    assert instr.last_unique_crash()
    drv.cleanup()
    instr.cleanup()


def test_new_path_counts_exact(corpus_bin):
    """Prefix-matching inputs produce exactly one new path each, in
    any order of discovery depth (reference smoke-test's exact
    new-path-count assertions)."""
    drv, instr, _ = make_stack(corpus_bin)
    inputs = [b"zzzz", b"Azzz", b"ABzz", b"ABCz"]
    new_paths = 0
    for s in inputs:
        drv.test_input(s)
        new_paths += int(instr.is_new_path() > 0)
    assert new_paths == 4
    # replays add nothing
    for s in inputs:
        drv.test_input(s)
        assert instr.is_new_path() == 0
    drv.cleanup()
    instr.cleanup()


def test_bit_flip_finds_crash_from_close_seed(corpus_bin):
    """Seed 'ABC@' is one bit from 'ABCD': deterministic bit_flip must
    find the crash within its 32 flips (reference README scenario)."""
    drv, instr, _ = make_stack(corpus_bin, mutator="bit_flip")
    fz = Fuzzer(drv, write_findings=False, batch_size=8)
    stats = fz.run(32)
    assert stats.crashes >= 1
    assert stats.unique_crashes >= 1
    assert stats.new_paths >= 2
    drv.cleanup()
    instr.cleanup()


def test_batched_matches_single_exec_counts(corpus_bin):
    """The batched TPU-triage path reports the same unique new-path
    set as the single-exec loop on the same candidate stream."""
    drv1, instr1, _ = make_stack(corpus_bin, mutator="bit_flip")
    fz1 = Fuzzer(drv1, write_findings=False, batch_size=8)
    s1 = fz1.run(32)

    drv2, instr2, _ = make_stack(corpus_bin, mutator="bit_flip")
    # batch_size=1 forces one-lane batches through the same machinery
    fz2 = Fuzzer(drv2, write_findings=False, batch_size=1)
    s2 = fz2.run(32)
    assert s1.crashes == s2.crashes
    assert s1.new_paths == s2.new_paths
    for d, i in ((drv1, instr1), (drv2, instr2)):
        d.cleanup()
        i.cleanup()


def test_file_driver_batched(corpus_bin):
    drv, instr, _ = make_stack(corpus_bin, mutator="bit_flip",
                               driver="file")
    fz = Fuzzer(drv, write_findings=False, batch_size=16)
    stats = fz.run(32)
    assert stats.crashes >= 1
    drv.cleanup()
    instr.cleanup()


def test_state_roundtrip_and_merge(corpus_bin):
    drv, instr, _ = make_stack(corpus_bin)
    drv.test_input(b"zzzz")
    drv.test_input(b"Azzz")
    state = instr.get_state()
    d = json.loads(state)
    assert d["instrumentation"] == "afl"
    assert d["total_execs"] == 2

    fresh = instrumentation_factory("afl", None)
    fresh.set_state(state)
    assert fresh.total_execs == 2
    assert np.array_equal(fresh.virgin_bits, instr.virgin_bits)

    # merge: disjoint coverage ANDs together
    other = instrumentation_factory("afl", None)
    drv2, instr2, _ = make_stack(corpus_bin)
    drv2.test_input(b"ABzz")
    other.merge(instr2.get_state())
    other.merge(state)
    both = (np.asarray(other.virgin_bits) != 0xFF).sum()
    assert both >= (np.asarray(instr.virgin_bits) != 0xFF).sum()
    for d_, i_ in ((drv, instr), (drv2, instr2)):
        d_.cleanup()
        i_.cleanup()


def test_persistence_option(corpus_bin):
    instr = instrumentation_factory(
        "afl", json.dumps({"persistence_max_cnt": 8}))
    mut = mutator_factory("havoc", '{"seed": 7}', b"ABC@")
    drv = driver_factory(
        "stdin", json.dumps({"path": corpus_bin("test-persist")}),
        instr, mut)
    fz = Fuzzer(drv, write_findings=False, batch_size=64)
    stats = fz.run(256)
    assert stats.iterations == 256
    assert stats.errors == 0
    drv.cleanup()
    instr.cleanup()


def test_no_forkserver_mode(corpus_bin):
    instr = instrumentation_factory(
        "afl", json.dumps({"use_fork_server": 0}))
    mut = mutator_factory("bit_flip", None, b"ABC@")
    drv = driver_factory(
        "stdin", json.dumps({"path": corpus_bin("test")}), instr, mut)
    fz = Fuzzer(drv, write_findings=False, batch_size=8)
    stats = fz.run(32)
    assert stats.crashes >= 1
    drv.cleanup()
    instr.cleanup()


def test_qemu_mode_defaults_to_bundled_tracer():
    """qemu_mode without qemu_path resolves to the bundled kb-trace
    binary-only tracer (built on demand); an explicit nonexistent
    path still fails loudly."""
    instr = instrumentation_factory("afl", json.dumps({"qemu_mode": 1}))
    assert instr.options["qemu_path"].endswith("kb-trace")
    assert os.path.exists(instr.options["qemu_path"])
    instr.cleanup()
    with pytest.raises(ValueError, match="qemu"):
        instrumentation_factory("afl", json.dumps(
            {"qemu_mode": 1, "qemu_path": "/nonexistent"}))


def test_word_skip_triage_matches_per_lane_loop():
    """The word-skip batch triage (afl.py _np_triage_batch) must be
    bit-identical to the per-lane classify + has_new_bits fold it
    replaced — new-path returns, crash/hang uniqueness, and all
    three virgin maps, across densities and in-batch duplicates."""
    from killerbeez_tpu import FUZZ_CRASH, FUZZ_HANG, MAP_SIZE
    from killerbeez_tpu.instrumentation.afl import (
        _np_classify, _np_has_new_bits,
    )

    def ref_triage(instr, bitmaps, verdicts):
        n = len(bitmaps)
        np_, uc, uh = (np.zeros(n, np.int32), np.zeros(n, bool),
                       np.zeros(n, bool))
        for i in range(n):
            cls = _np_classify(bitmaps[i])
            np_[i], instr.virgin_bits = _np_has_new_bits(
                instr.virgin_bits, cls)
            simp = np.where(bitmaps[i] == 0, 1, 128).astype(np.uint8)
            if verdicts[i] == FUZZ_CRASH:
                r, instr.virgin_crash = _np_has_new_bits(
                    instr.virgin_crash, simp)
                uc[i] = r > 0
            elif verdicts[i] == FUZZ_HANG:
                r, instr.virgin_tmout = _np_has_new_bits(
                    instr.virgin_tmout, simp)
                uh[i] = r > 0
        return np_, uc, uh

    rng = np.random.default_rng(7)
    a = instrumentation_factory("afl", None)
    b = instrumentation_factory("afl", None)
    for trial in range(4):
        n = 40
        maps = np.zeros((n, MAP_SIZE), np.uint8)
        idx = rng.integers(0, MAP_SIZE,
                           (6, int(MAP_SIZE * rng.uniform(5e-4, 8e-3))))
        for i in range(n):  # duplicates within the batch on purpose
            maps[i, idx[i % 6]] = rng.integers(1, 255)
        verd = rng.choice([0, FUZZ_CRASH, FUZZ_HANG], n,
                          p=[0.7, 0.15, 0.15]).astype(np.int32)
        ra = a._np_triage_batch(maps, verd)
        rb = ref_triage(b, maps, verd)
        for x, y in zip(ra, rb):
            np.testing.assert_array_equal(x, y, err_msg=f"trial {trial}")
    np.testing.assert_array_equal(a.virgin_bits, b.virgin_bits)
    np.testing.assert_array_equal(a.virgin_crash, b.virgin_crash)
    np.testing.assert_array_equal(a.virgin_tmout, b.virgin_tmout)
