"""Mutator engine tests: walking-order parity, determinism,
batch==single consistency, state resume, multipart contract."""

import json

import numpy as np
import pytest

from killerbeez_tpu.mutators import (
    MUTATE_MULTIPLE_INPUTS, mutator_factory, mutator_help, mutator_names,
)
from killerbeez_tpu.ops.mutate_core import (
    ARITH_MAX, INTERESTING_8, arithmetic_total, bit_flip_total,
    interesting_total,
)
from killerbeez_tpu.utils.serialization import encode_mem_array

SEED = b"ABC@"


def test_factory_names_and_help():
    names = mutator_names()
    for expected in ("bit_flip", "arithmetic", "interesting_value", "havoc",
                     "nop", "ni", "zzuf", "afl", "honggfuzz", "dictionary",
                     "splice", "manager", "radamsa"):
        assert expected in names
    with pytest.raises(ValueError, match="unknown mutator"):
        mutator_factory("bitflipper", None, SEED)
    h = mutator_help()
    assert "bit_flip" in h and "ratio" in h


def test_nop():
    m = mutator_factory("nop", None, SEED)
    assert m.mutate() == SEED
    assert m.mutate() == SEED
    assert m.get_current_iteration() == 2
    assert m.get_total_iteration_count() == -1


def test_bit_flip_walk_order():
    m = mutator_factory("bit_flip", None, SEED)
    total = m.get_total_iteration_count()
    assert total == bit_flip_total(len(SEED), 1) == 32
    outs = [m.mutate() for _ in range(total)]
    assert m.mutate() is None  # exhausted -> the C API's 0 return
    for i, out in enumerate(outs):
        want = bytearray(SEED)
        want[i // 8] ^= 128 >> (i % 8)  # AFL FLIP_BIT, MSB-first
        assert out == bytes(want), i
    # seed "ABC@" is one bit from "ABCD": flipping bit of 0x40->0x44
    assert SEED[3] == 0x40
    assert b"ABCD" in outs


def test_bit_flip_batch_equals_singles():
    m1 = mutator_factory("bit_flip", None, SEED)
    m2 = mutator_factory("bit_flip", None, SEED)
    bufs, lens = m1.mutate_batch(10)
    for i in range(10):
        assert m2.mutate() == bufs[i, :lens[i]].tobytes()


def test_bit_flip_num_bits_and_overclamp():
    m = mutator_factory("bit_flip", '{"num_bits": 4}', SEED)
    assert m.get_total_iteration_count() == 29
    out = m.mutate()
    want = bytearray(SEED)
    want[0] ^= 0b11110000
    assert out == bytes(want)
    with pytest.raises(ValueError):
        mutator_factory("bit_flip", '{"num_bits": 3}', SEED)


def test_bit_flip_exhaustion_batch_guard():
    m = mutator_factory("bit_flip", None, SEED)
    with pytest.raises(ValueError, match="left"):
        m.mutate_batch(33)
    m.mutate_batch(32)
    assert m.remaining() == 0


def test_arithmetic_walk_start():
    m = mutator_factory("arithmetic", None, SEED)
    assert m.get_total_iteration_count() == arithmetic_total(len(SEED))
    outs = [m.mutate() for _ in range(4)]
    # width-1 stage, pos 0: +1, -1, +2, -2
    assert outs[0][0] == (SEED[0] + 1) & 0xFF
    assert outs[1][0] == (SEED[0] - 1) & 0xFF
    assert outs[2][0] == (SEED[0] + 2) & 0xFF
    assert outs[3][0] == (SEED[0] - 2) & 0xFF
    for o in outs:
        assert o[1:4] == SEED[1:4]


def test_arithmetic_covers_all_stages():
    m = mutator_factory("arithmetic", None, SEED)
    total = m.get_total_iteration_count()
    # 1B: 4 pos; 2B: 3 pos x LE/BE; 4B: 1 pos x LE/BE — x35 deltas x2 signs
    assert total == (4 * 35 * 2) + (3 * 35 * 2 * 2) + (1 * 35 * 2 * 2)
    bufs, lens = m.mutate_batch(total)
    assert (lens == len(SEED)).all()
    # every candidate differs from the seed
    seed_arr = np.frombuffer(SEED, dtype=np.uint8)
    assert (bufs[:, :4] != seed_arr).any(axis=1).all()


def test_interesting_value_walk_start():
    m = mutator_factory("interesting_value", None, SEED)
    assert m.get_total_iteration_count() == interesting_total(len(SEED))
    out = m.mutate()
    assert out[0] == INTERESTING_8[0] & 0xFF  # -128 -> 0x80
    assert out[1:4] == SEED[1:4]


def test_havoc_deterministic_and_batch_consistent():
    m1 = mutator_factory("havoc", '{"seed": 7}', SEED)
    m2 = mutator_factory("havoc", '{"seed": 7}', SEED)
    outs1 = [m1.mutate() for _ in range(8)]
    bufs, lens = m2.mutate_batch(8)
    for i in range(8):
        assert outs1[i] == bufs[i, :lens[i]].tobytes()
    m3 = mutator_factory("havoc", '{"seed": 8}', SEED)
    outs3 = [m3.mutate() for _ in range(8)]
    assert outs1 != outs3  # different PRNG seed -> different stream
    # lengths bounded by ratio*seed
    assert all(1 <= len(o) <= m1.max_length for o in outs1)


def test_havoc_bad_options():
    with pytest.raises(ValueError):
        mutator_factory("havoc", '{"stack_pow2": 9}', SEED)


def test_zzuf_flips_only_within_length():
    m = mutator_factory("zzuf", '{"ratio_bits": 0.5, "seed": 3}', b"AAAA")
    bufs, lens = m.mutate_batch(16)
    assert (lens == 4).all()
    assert (bufs[:, 4:] == 0).all()  # padding untouched
    assert (bufs[:, :4] != ord("A")).any()  # something flipped at p=.5


def test_ni_swaps_chunks():
    m = mutator_factory("ni", '{"seed": 1}', bytes(range(32)))
    outs = [m.mutate() for _ in range(8)]
    assert all(len(o) == 32 for o in outs)
    assert any(o != bytes(range(32)) for o in outs)


def test_honggfuzz_mangle():
    m = mutator_factory("honggfuzz", '{"seed": 5}', b"0123456789")
    outs = [m.mutate() for _ in range(8)]
    assert any(o != b"0123456789" for o in outs)
    m2 = mutator_factory("honggfuzz", '{"seed": 5}', b"0123456789")
    assert [m2.mutate() for _ in range(8)] == outs


def test_dictionary_overwrite_then_insert():
    m = mutator_factory("dictionary", '{"tokens": ["XY"]}', SEED)
    assert m.get_total_iteration_count() == 2 * len(SEED)
    outs = [m.mutate() for _ in range(m.get_total_iteration_count())]
    # first half: overwrite at each position
    assert outs[0][:2] == b"XY" and outs[0][2:4] == SEED[2:4]
    assert outs[1][0:1] == SEED[0:1] and outs[1][1:3] == b"XY"
    # second half: insert at each position
    ins0 = outs[len(SEED)]
    assert ins0[:2] == b"XY" and ins0[2:6] == SEED
    assert m.mutate() is None


def test_splice_head_a_tail_b():
    m = mutator_factory("splice", '{"corpus": ["WXYZ9876"], "seed": 2}',
                        SEED)
    outs = [m.mutate() for _ in range(8)]
    partner = b"WXYZ9876"
    for o in outs:
        assert o[0:1] == SEED[0:1]  # head starts with seed bytes
        # head is a prefix of the seed, tail a contiguous run of the
        # partner (possibly clamped at the buffer boundary)
        head_len = 0
        while head_len < min(len(o), len(SEED)) and \
                o[head_len] == SEED[head_len]:
            head_len += 1
        assert 1 <= head_len < len(o)
        assert o[head_len:] in partner


def test_afl_pipeline_stages():
    m = mutator_factory("afl", None, SEED)
    assert m.get_total_iteration_count() == -1
    assert m.stage_name() == "flip1"
    ref = mutator_factory("bit_flip", None, SEED)
    for _ in range(32):  # first stage identical to bit_flip walk
        assert m.mutate() == ref.mutate()
    assert m.stage_name() == "flip2"
    # run through all deterministic stages into havoc
    while m.stage_name() != "havoc":
        assert m.mutate() is not None
    assert m.iteration == m.det_total
    out = m.mutate()  # havoc tail works
    assert out is not None


def test_afl_skip_deterministic():
    m = mutator_factory("afl", '{"skip_deterministic": 1}', SEED)
    assert m.stage_name() == "havoc"
    assert m.det_total == 0


def test_afl_batch_spans_stage_boundary():
    m = mutator_factory("afl", None, SEED)
    singles = mutator_factory("afl", None, SEED)
    bufs, lens = m.mutate_batch(40)  # crosses flip1(32) -> flip2
    for i in range(40):
        assert singles.mutate() == bufs[i, :lens[i]].tobytes(), i


def test_state_resume_deterministic():
    m = mutator_factory("bit_flip", None, SEED)
    for _ in range(5):
        m.mutate()
    state = m.get_state()
    next_out = m.mutate()
    m2 = mutator_factory("bit_flip", None, b"zz")  # different seed input
    m2.set_state(state)
    assert m2.mutate() == next_out
    assert m2.get_current_iteration() == 6


def test_state_rejects_wrong_mutator():
    m = mutator_factory("bit_flip", None, SEED)
    with pytest.raises(ValueError):
        m.set_state(json.dumps({"mutator": "havoc", "iteration": 1}))


def test_set_input_resets_walk():
    m = mutator_factory("bit_flip", None, SEED)
    m.mutate()
    m.set_input(b"QQQQQQQQ")
    assert m.get_current_iteration() == 0
    assert m.get_total_iteration_count() == 64
    out = m.mutate()
    assert out[0] == ord("Q") ^ 0x80


def test_manager_multipart():
    parts = [b"AAAA", b"BBBB"]
    seed = encode_mem_array(parts).encode()
    m = mutator_factory(
        "manager", '{"mutators": ["bit_flip", "bit_flip"]}', seed)
    num, sizes = m.get_input_info()
    assert num == 2 and sizes == [4, 4]
    # part-0 request advances; both parts retrievable
    p0 = m.mutate_extended(MUTATE_MULTIPLE_INPUTS | 0)
    p1 = m.mutate_extended(MUTATE_MULTIPLE_INPUTS | 1)
    assert p0 is not None and p1 is not None
    assert len(p0) == 4 and len(p1) == 4
    # round-robin: first advance mutated part 0, second mutates part 1
    whole1 = m.mutate()
    assert whole1 is not None and len(whole1) == 8
    # finite children -> finite total (2 walks of 32)
    assert m.get_total_iteration_count() == 64
    # state round-trip
    st = m.get_state()
    m2 = mutator_factory(
        "manager", '{"mutators": ["bit_flip", "bit_flip"]}', seed)
    m2.set_state(st)
    assert m2.mutate() == m.mutate()


def test_manager_part_count_mismatch():
    seed = encode_mem_array([b"AAAA"]).encode()
    with pytest.raises(ValueError, match="parts"):
        mutator_factory("manager",
                        '{"mutators": ["bit_flip", "bit_flip"]}', seed)


def test_radamsa_gated():
    import shutil
    if shutil.which("radamsa"):
        pytest.skip("radamsa present; gating not triggerable")
    with pytest.raises(ValueError, match="radamsa"):
        mutator_factory("radamsa", None, SEED)


def test_empty_seed_rejected():
    with pytest.raises(ValueError, match="empty seed"):
        mutator_factory("bit_flip", None, b"")


# -- focused mutation (crack-stage byte masks) ------------------------

def test_focus_mask_none_is_bit_exact_parity():
    """The unfocused path is parity-pinned: installing and clearing
    a mask (or never touching it) yields the identical candidate
    stream — same compiled fn, same RNG draws."""
    seed = bytes(range(16))
    ref = mutator_factory("havoc", '{"seed": 5}', seed)
    rb, rl = ref.mutate_batch(32)
    for prep in (lambda m: None,
                 lambda m: m.set_focus_mask(None),
                 lambda m: (m.set_focus_mask([2, 3]),
                            m.set_focus_mask(None))):
        m = mutator_factory("havoc", '{"seed": 5}', seed)
        prep(m)
        b, l = m.mutate_batch(32)
        assert np.array_equal(np.asarray(b), np.asarray(rb))
        assert np.array_equal(np.asarray(l), np.asarray(rl))


def test_focus_mask_anchors_havoc_edits():
    """With a mask, primary edit positions anchor on the mask bytes:
    masked positions mutate far more often than distant ones, and a
    single-byte mask at 0 never touches the buffer tail (block edits
    extend right of the anchor only up to length//2)."""
    seed = bytes(range(16))
    m = mutator_factory("havoc", '{"seed": 5}', seed)
    m.set_focus_mask([3])
    b, l = m.mutate_batch(256)
    b, l = np.asarray(b), np.asarray(l)
    sb = np.frombuffer(seed, np.uint8)
    diff = (b[l == 16][:, :16] != sb[None, :]).sum(0)
    assert diff[3] > 10 * max(int(diff[15]), 1)
    assert diff[:3].sum() == 0          # nothing lands left of the mask


def test_focus_mask_zzuf_strictly_masked():
    seed = bytes(range(16))
    m = mutator_factory("zzuf", '{"seed": 5, "ratio_bits": 0.2}', seed)
    m.set_focus_mask([2, 7])
    b, _ = m.mutate_batch(64)
    diff = np.flatnonzero(
        (np.asarray(b)[:, :16] != np.frombuffer(seed, np.uint8)).any(0))
    assert set(diff.tolist()) <= {2, 7}
    assert len(diff)                    # and it DOES mutate them


def test_focus_mask_afl_tail_only():
    """The afl mutator's deterministic stages keep their exact walk
    under a mask; only the havoc tail focuses."""
    seed = b"ABCDEFGH"
    ref = mutator_factory("afl", None, seed)
    m = mutator_factory("afl", None, seed)
    m.set_focus_mask([1])
    rb, _ = ref.mutate_batch(16)        # deep inside bit_flip 1
    fb, _ = m.mutate_batch(16)
    assert np.array_equal(np.asarray(rb), np.asarray(fb))


def test_focus_mask_validation_and_clearing():
    seed = bytes(range(16))
    m = mutator_factory("havoc", None, seed)
    m.set_focus_mask([500, -3])         # all out of the buffer
    assert m.focus_positions is None    # empty mask clears, not pins
    m.set_focus_mask([1, 1, 5])
    assert m.focus_positions.tolist() == [1, 5]
    m.set_focus_mask([])
    assert m.focus_positions is None


# -- grammar-structured mutation (killerbeez_tpu/grammar/) ------------

def test_grammar_mutator_registered():
    assert "grammar" in mutator_names()
    assert "structure" in mutator_help()


def test_grammar_mutator_degenerate_is_bit_exact_havoc_parity():
    """The host-path parity anchor: the degenerate grammar's
    candidate stream is the havoc stream, bit for bit."""
    seed = bytes(range(16))
    ref = mutator_factory("havoc", '{"seed": 5}', seed)
    m = mutator_factory("grammar", '{"seed": 5}', seed)
    rb, rl = ref.mutate_batch(64)
    gb, gl = m.mutate_batch(64)
    assert np.array_equal(np.asarray(rb), np.asarray(gb))
    assert np.array_equal(np.asarray(rl), np.asarray(gl))


def test_grammar_mutator_structured_diverges_deterministically():
    from killerbeez_tpu.models.zoo import build_zoo
    t = build_zoo("zoo:tlv:depth=2,bug=1")
    opts = json.dumps({"seed": 5, "grammar": t.grammar.to_json(),
                       "grammar_stage": 256})
    ref = mutator_factory("havoc", '{"seed": 5}', t.seed)
    a = mutator_factory("grammar", opts, t.seed)
    b = mutator_factory("grammar", opts, t.seed)
    rb, _ = ref.mutate_batch(64)
    ab, al = a.mutate_batch(64)
    bb, bl = b.mutate_batch(64)
    assert not np.array_equal(np.asarray(rb), np.asarray(ab))
    assert np.array_equal(np.asarray(ab), np.asarray(bb))
    assert np.array_equal(np.asarray(al), np.asarray(bl))


def test_grammar_mutator_auto_needs_target():
    with pytest.raises(ValueError, match="target"):
        mutator_factory("grammar", '{"grammar": "auto"}', SEED)
    m = mutator_factory(
        "grammar", '{"grammar": "auto", "target": "test"}', SEED)
    assert m.grammar_tables.nondegen


def test_manager_framed_grammar_children_roundtrip():
    """Satellite property: frame -> structured-mutate -> reframe ->
    unframe round-trips.  Message boundaries survive ANY grammar
    child mutation by construction, and the recomposed frame is the
    candidate byte stream itself."""
    from killerbeez_tpu.models.zoo import build_zoo
    from killerbeez_tpu.stateful.framing import (
        MAX_MSG_LEN, frame_messages, unframe,
    )
    t = build_zoo("zoo:chain:width=3,bug=1")
    gopts = {"seed": 9, "grammar": t.grammar.to_json(),
             "grammar_stage": 256}
    parts = [t.seed, t.seed]
    seed = frame_messages(parts, 4)
    m = mutator_factory("manager", json.dumps(
        {"mutators": ["grammar", "grammar"],
         "mutator_options": [gopts, gopts],
         "framed": 1, "m_max": 4}), seed)
    assert [p for p in m.parts] == parts
    for _ in range(32):
        out = m.mutate()
        assert out is not None
        msgs = unframe(out, 4)
        # boundaries survive: the parse recovers each child's
        # current candidate exactly, and reframing reproduces the
        # byte stream
        assert len(msgs) == len(parts)
        assert all(len(p) <= MAX_MSG_LEN for p in msgs)
        assert frame_messages(msgs, 4) == out
        assert msgs == m.current
