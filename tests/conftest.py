"""Test config: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on
XLA's forced host platform device count.

Note: the environment's sitecustomize imports jax at interpreter
start (axon TPU tunnel), so env vars are too late here — the platform
must be forced through jax.config before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import subprocess  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS_BUILD = os.path.join(REPO_ROOT, "corpus", "build")


def pytest_collection_modifyitems(items):
    """Auto-apply the capability markers registered in pyproject:
    ``native`` for anything that builds/uses the host toolchain
    fixtures (the corpus_bin fixture is the tell), ``device`` for the
    TPU-hardware gate file.  `-m 'not native'` then runs cleanly on
    toolchain-less hosts without touching every test."""
    for item in items:
        if "corpus_bin" in getattr(item, "fixturenames", ()):
            item.add_marker(pytest.mark.native)
        if os.path.basename(str(item.fspath)) in (
                "test_native_exec.py", "test_tpu_gate.py"):
            item.add_marker(
                pytest.mark.device
                if "tpu_gate" in str(item.fspath)
                else pytest.mark.native)


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture(scope="session")
def corpus_bin():
    """Build native/ + corpus/ fixtures once; returns a path resolver.
    Skips dependent tests when the host toolchain can't build them."""
    from killerbeez_tpu.native.build import build_error, build_native
    if not build_native():
        pytest.skip(f"native build unavailable: {build_error()}")
    proc = subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "corpus")],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        pytest.skip(f"corpus build failed: {proc.stderr[-500:]}")

    def path(name: str) -> str:
        return os.path.join(CORPUS_BUILD, name)

    return path


@pytest.fixture(scope="session")
def kb_trace_usable(corpus_bin):
    """Gate for tests that execute targets under the kb-trace ptrace
    single-step tracer (qemu_mode default): tracing speed is kernel-
    dependent — on hosts where PTRACE_SINGLESTEP round-trips are slow
    (observed ~10 s for the trivial test-plain binary on some
    sandboxed 4.x kernels vs milliseconds on bare metal), every
    traced exec blows the 2 s hang budget and the verdicts read as
    hangs.  Probe once per session with a hard deadline and skip with
    the measured number instead of failing on timing."""
    import time

    from killerbeez_tpu.native.build import kb_trace_path
    deadline = 2.0                       # the afl tier's hang budget
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [kb_trace_path(), corpus_bin("test-plain")],
            input=b"zzzz", capture_output=True, timeout=deadline)
        ok = proc.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    elapsed = time.monotonic() - t0
    if not ok:
        pytest.skip(
            "kb-trace single-step tracing too slow on this kernel "
            f"(> {deadline:.0f}s for a trivial binary, measured "
            f"{elapsed:.1f}s+): traced execs would all misreport as "
            "hangs")
    return True
