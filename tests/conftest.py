"""Test config: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on
XLA's forced host platform device count. Must be set before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
