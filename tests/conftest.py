"""Test config: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on
XLA's forced host platform device count.

Note: the environment's sitecustomize imports jax at interpreter
start (axon TPU tunnel), so env vars are too late here — the platform
must be forced through jax.config before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
