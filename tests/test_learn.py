"""Learned mutation shaping (ISSUE 14, killerbeez_tpu/learn/): a
byte-saliency model trained from corpus lineage, inference inside the
device generation scan.

Pins the tier's contracts:
  * the PARITY ANCHOR — a version-0 model emits logit exactly 0.0,
    quantizes to the all-ones mask, and the masked havoc kernel with
    an all-ones mask is bit-identical to ``havoc_at``; the shaped
    generation scans (single-chip -G and dp>1 mesh, feedback on and
    off) are then bit-identical to the unshaped scans — findings,
    virgin maps AND corpus-store write-through;
  * the model learns: synthetic positional labels converge to a mask
    selecting exactly the labeled positions;
  * provenance sidecars round-trip (and pre-learn sidecars load
    unchanged), the quarantine validator accepts/bounds the field,
    kb-corpus summarizes label coverage;
  * the loop end-to-end: labels flow from admissions, training runs
    between dispatches, learn_update events + counters/gauges fold
    through aggregate.merge, checkpoint/--resume restores the model
    and rebuilds labels from sidecars;
  * the fixedform_vm family certificate: the padding regions carry
    NO branch dependency (dataflow-exact), which is what makes the
    bench gate's uplift claim honest.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from killerbeez_tpu.corpus.quarantine import EntryValidator
from killerbeez_tpu.corpus.store import CorpusEntry, CorpusStore
from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.fuzzer.loop import Fuzzer
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.learn import LearnTier, dataset, model
from killerbeez_tpu.mutators.factory import mutator_factory
from killerbeez_tpu.ops import mutate_core as mc

SEED = b"ABCD1234"


# ---------------------------------------------------------------------------
# model: the parity anchor + learnability
# ---------------------------------------------------------------------------


def test_v0_model_logits_exactly_zero_all_ones_mask():
    """init_params zeroes the output layer: logits are EXACTLY 0.0
    (not merely small) for arbitrary inputs, and the quantized mask
    is all-ones — the anchor the whole parity story rests on."""
    p = model.init_params()
    rng = np.random.default_rng(0)
    for ln in (1, 7, 16):
        buf = jnp.asarray(rng.integers(0, 256, 32).astype(np.uint8))
        lg = model.saliency_logits(p, buf, jnp.int32(ln))
        assert float(jnp.max(jnp.abs(lg))) == 0.0
        m = np.asarray(model.quantize_mask(lg, jnp.int32(ln)))
        assert m.tolist() == [1] * 32   # past-prefix stays mutable


@pytest.mark.parametrize("case_seed", [0, 7, 91])
def test_masked_havoc_all_ones_bit_identical(case_seed):
    """havoc_mask_at with an all-ones mask == havoc_at, byte for
    byte, over random seeds/lengths/keys; an all-ZERO mask falls
    back to uniform (never pins mutation to nothing)."""
    rng = np.random.default_rng(case_seed)
    for _ in range(10):
        L = int(rng.choice([16, 24, 64]))
        ln = int(rng.integers(1, L + 1))
        buf = jnp.asarray(rng.integers(0, 256, L).astype(np.uint8))
        key = jax.random.key(int(rng.integers(0, 2**31)))
        a, la = mc.havoc_at(buf, jnp.int32(ln), key, stack_pow2=4)
        b, lb = mc.havoc_mask_at(buf, jnp.int32(ln), key,
                                 jnp.ones((L,), jnp.uint8),
                                 stack_pow2=4)
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert int(la) == int(lb)
        c, lc = mc.havoc_mask_at(buf, jnp.int32(ln), key,
                                 jnp.zeros((L,), jnp.uint8),
                                 stack_pow2=4)
        assert np.array_equal(np.asarray(a), np.asarray(c))
        assert int(la) == int(lc)


def test_model_learns_synthetic_positions():
    """Positions 0..3 labeled positive, the rest negative, across
    random 24-byte parents: after one training round the mask keeps
    exactly the labeled positions (on unseen buffers too)."""
    lb = dataset.LabelBuffer()
    rng = np.random.default_rng(1)
    for i in range(40):
        buf = rng.integers(0, 256, 24).astype(np.uint8).tobytes()
        lb.add(f"p{i}", buf, [0, 1, 2, 3], 1)
        lb.add(f"p{i}", buf, list(range(4, 24)), 0, cap=8)
    tier = LearnTier(train_interval_s=0.0, min_labels=10,
                     steps_per_round=50)
    tier.labels = lb
    loss = tier.train_round()
    assert tier.version == 1 and loss is not None and loss < 0.2
    unseen = bytes(rng.integers(0, 256, 24, dtype=np.uint8))
    pos = tier.focus_positions_for(unseen)
    assert pos is not None
    # pad_pow2 is the mutator's job; the tier returns the raw set
    assert sorted(set(pos)) == [0, 1, 2, 3]


def test_training_survives_label_buffer_saturation():
    """REGRESSION: once the FIFO label buffer saturates, len(labels)
    pins at cap — the new-labels signal must read the MONOTONE
    intake counter or training silently freezes for the rest of the
    campaign while masks keep being applied."""
    tier = LearnTier(train_interval_s=0.0, min_labels=4,
                     steps_per_round=1, sample_cap=32)
    rng = np.random.default_rng(5)

    def feed(n):
        for _ in range(n):
            buf = rng.integers(0, 256, 16).astype(np.uint8).tobytes()
            tier.labels.add("k" + str(rng.integers(1 << 30)), buf,
                            [0, 1], 1)
            tier.labels.add("k" + str(rng.integers(1 << 30)), buf,
                            [4, 5], 0)

    feed(20)                             # well past cap=32 samples
    assert len(tier.labels) == 32        # saturated
    assert tier.train_round() is not None
    v = tier.version
    feed(5)                              # fresh labels, len still 32
    assert len(tier.labels) == 32
    assert tier.ready_to_train()
    assert tier.maybe_train() and tier.version == v + 1
    # and with NO fresh labels the round is skipped
    assert not tier.ready_to_train()


def test_resume_bootstrap_honors_informative_diff(tmp_path):
    """REGRESSION: sidecar replay must apply the TIER'S live
    informative-diff threshold, not a looser module constant — a
    resumed campaign trains on exactly the samples the uninterrupted
    one accepted."""
    parent = bytes(64)
    tier = LearnTier()
    tier.informative_diff = 4
    wide = bytearray(parent)
    for p in range(8):                   # 8-position diff: > 4
        wide[p] ^= 0xFF
    prov = dataset.make_provenance(parent, bytes(wide), "havoc")
    entries = [CorpusEntry(bytes(wide), parent="base",
                           provenance=prov)]
    used = tier.bootstrap(entries, lambda k: parent)
    assert used == 0 and tier.labels.positives == 0
    tier.informative_diff = 16           # now inside the threshold
    assert tier.bootstrap(entries, lambda k: parent) == 1
    assert tier.labels.positives > 0


def test_focus_mask_pad_pow2_shape_stability():
    """set_focus_mask(pad_pow2=True) cycles positions to the next
    power-of-two length (log2 compiled shapes instead of one per
    mask size); padding stays inside the mask set."""
    mut = mutator_factory("havoc", None, SEED)
    mut.set_focus_mask([1, 3, 6], pad_pow2=True)
    got = mut.focus_positions.tolist()
    assert len(got) == 4 and set(got) == {1, 3, 6}
    mut.set_focus_mask([1, 3, 6])            # default: exact set
    assert mut.focus_positions.tolist() == [1, 3, 6]
    mut.set_focus_mask(None)
    assert mut.focus_positions is None


# ---------------------------------------------------------------------------
# dataset: diffs, provenance codec, informative-diff rule
# ---------------------------------------------------------------------------


def test_diff_bitmap_and_b64_roundtrip():
    parent = b"\x00" * 8
    child = b"\x00\xFF\x00\x00\xAA\x00\x00\x00\x11\x22"
    bm = dataset.diff_bitmap(parent, child)
    assert bm.tolist() == [0, 1, 0, 0, 1, 0, 0, 0, 1, 1]
    s = dataset.bitmap_to_b64(bm)
    back = dataset.b64_to_bitmap(s, len(bm))
    assert back.tolist() == bm.tolist()
    assert dataset.b64_to_bitmap("not base64!!", 4) is None


def test_provenance_record_and_positions():
    prov = dataset.make_provenance(b"AAAA", b"ABAA", "havoc",
                                   "havoc")
    assert prov["mutator"] == "havoc" and prov["bytes"] == 1
    pos = dataset.provenance_positions(prov, 4)
    assert pos.tolist() == [1]
    assert dataset.provenance_positions({"bitmap": 7}, 4) is None


def test_informative_diff_rule():
    """A smeared (block-op) diff contributes NO positive labels —
    large diffs carry ~no positional signal — while its provenance
    record is still produced for the sidecar."""
    tier = LearnTier()
    parent = bytes(range(64))
    smeared = bytes(64)                      # every byte differs
    prov = tier.note_admission("p", parent, smeared, "havoc")
    assert prov is not None and prov["bytes"] == 63  # byte 0 matches
    assert tier.labels.positives == 0
    small = bytearray(parent)
    small[5] ^= 0xFF
    tier.note_admission("p", parent, bytes(small), "havoc")
    assert tier.labels.positives == 1


# ---------------------------------------------------------------------------
# the parity suite: shaped scans == unshaped scans at version 0
# ---------------------------------------------------------------------------


def _findings(root):
    out = {}
    for kind in ("crashes", "hangs", "new_paths"):
        d = os.path.join(root, kind)
        out[kind] = sorted(
            f for f in (os.listdir(d) if os.path.isdir(d) else [])
            if len(f) == 32)
    return out


@pytest.mark.parametrize("reseed", [False, True])
def test_generation_scan_learn_v0_parity_single_chip(reseed):
    """The shaped single-chip generation scan with version-0 weights
    is bit-identical to the unshaped scan: findings ring, admission
    ledger AND virgin maps — reseeding on and off."""
    def run(learn):
        instr = instrumentation_factory("jit_harness",
                                        '{"target": "test"}')
        mut = mutator_factory("havoc", '{"seed": 7}', SEED)
        if learn:
            instr.learn_params = model.init_params()
        its = mut.peek_iterations(64)
        out = instr.run_batch_generations(mut, its, 4, pad_to=64,
                                          reseed=reseed)
        return out.materialize(), instr

    h0, i0 = run(False)
    h1, i1 = run(True)
    assert int(h0.fr_ptr) == int(h1.fr_ptr)
    st = min(int(h0.fr_ptr), int(h0.cap))
    assert st > 0, "nothing found — the comparison is vacuous"
    assert np.array_equal(h0.fr_bufs[:st], h1.fr_bufs[:st])
    assert np.array_equal(h0.fr_pack[:st], h1.fr_pack[:st])
    assert np.array_equal(h0.adm_bufs, h1.adm_bufs)
    for a, b in ((i0.virgin_bits, i1.virgin_bits),
                 (i0.virgin_crash, i1.virgin_crash),
                 (i0.virgin_tmout, i1.virgin_tmout)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def _trained_params():
    """A model whose logits are NOT uniform (some positions masked
    out): one SGD round on synthetic labels — the regime where the
    per-slot mask cache carries real information."""
    rng = np.random.default_rng(5)
    params = model.init_params()
    bufs = rng.integers(0, 256, (64, 16), dtype=np.uint8)
    lens = np.full(64, 16, np.int32)
    positions = rng.integers(0, 16, 64).astype(np.int32)
    y = (positions < 4).astype(np.float32)     # early bytes "useful"
    X = model.batch_features(bufs, lens, positions)
    w = np.where(y > 0, 4.0, 1.0).astype(np.float32)
    for _ in range(60):
        params, _ = model.train_step(params, X, jnp.asarray(y),
                                     jnp.asarray(w), jnp.float32(0.5))
    return params


@pytest.mark.parametrize("reseed", [False, True])
def test_mask_cache_matches_fresh_inference(reseed):
    """ISSUE 15 satellite: the per-slot mask cache in the generation
    scan carry.  A TRAINED model run as ONE G=4 dispatch (cache hits
    on re-selected slots, invalidated on admission) must produce the
    same findings ring and virgin maps as four G=1 dispatches of the
    same campaign (every dispatch starts cache-cold, so every
    generation infers fresh) — cached mask == fresh mask, byte for
    byte, or the candidate streams diverge."""
    params = _trained_params()
    # the trained model must actually mask something, or the cache
    # parity is vacuously the v0 all-ones case
    lg = model.saliency_logits(params, jnp.asarray(
        np.frombuffer(SEED, np.uint8)), jnp.int32(len(SEED)))
    m = np.asarray(model.quantize_mask(lg, jnp.int32(len(SEED))))
    assert 0 < m[:len(SEED)].sum() < len(SEED), \
        "trained mask neither all-ones nor all-zero"

    def run(g_per_dispatch, dispatches):
        instr = instrumentation_factory("jit_harness",
                                        '{"target": "test"}')
        instr.learn_params = params
        mut = mutator_factory("havoc", '{"seed": 7}', SEED)
        outs = []
        for _ in range(dispatches):
            its = mut.peek_iterations(64)
            out = instr.run_batch_generations(
                mut, its, g_per_dispatch, pad_to=64, reseed=reseed)
            outs.append(out.materialize())
            mut.advance(64 * g_per_dispatch)
        return outs, instr

    big, i_big = run(4, 1)
    small, i_small = run(1, 4)
    big_bufs = big[0].fr_bufs[:min(int(big[0].fr_ptr), big[0].cap)]
    small_bufs = np.concatenate([
        o.fr_bufs[:min(int(o.fr_ptr), o.cap)] for o in small])
    assert len(big_bufs), "nothing found — the comparison is vacuous"
    assert np.array_equal(big_bufs, small_bufs)
    for a, b in ((i_big.virgin_bits, i_small.virgin_bits),
                 (i_big.virgin_crash, i_small.virgin_crash)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("feedback", [0, 8])
def test_generation_campaign_learn_v0_parity(tmp_path, feedback):
    """Full -G campaigns: a learn tier that never trains (version 0
    — min_labels out of reach) produces findings AND store
    write-through identical to a no-learn campaign, feedback on and
    off."""
    def run(name, learn):
        instr = instrumentation_factory(
            "jit_harness", '{"target": "test", "learn": %d}'
            % int(learn))
        mut = mutator_factory("havoc", '{"seed": 7}', SEED)
        drv = driver_factory("file", None, instr, mut)
        tier = LearnTier(min_labels=10**9) if learn else None
        fz = Fuzzer(drv, output_dir=str(tmp_path / name),
                    batch_size=64, feedback=feedback, generations=4,
                    corpus_dir=str(tmp_path / name / "corpus"),
                    learn=tier)
        fz.run(1024)
        return fz

    run("off", False)
    fz = run("on", True)
    assert fz.learn.version == 0       # the parity regime held
    assert _findings(str(tmp_path / "on")) == \
        _findings(str(tmp_path / "off"))
    assert _findings(str(tmp_path / "on"))["new_paths"], "vacuous"

    def entries(name):
        d = tmp_path / name / "corpus"
        return sorted(f for f in os.listdir(d) if len(f) == 32)

    assert entries("on") == entries("off")


@pytest.mark.parametrize("reseed", [False, True])
def test_mesh_generation_scan_learn_v0_parity(reseed):
    """The dp>1 mesh generation scan with version-0 weights is
    bit-identical to the unshaped mesh scan, per shard."""
    from killerbeez_tpu.parallel import ShardedCampaignDriver
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")

    def run(learn):
        instr = instrumentation_factory("jit_harness",
                                        '{"target": "test"}')
        mut = mutator_factory("havoc", '{"seed": 7}', SEED)
        drv = ShardedCampaignDriver("2,1", instr, mut,
                                    batch_size=128)
        if learn:
            instr.learn_params = model.init_params()
        out = drv.test_batch_generations(128, 4, reseed=reseed)
        return out.materialize(), instr

    h0, i0 = run(False)
    h1, i1 = run(True)
    found = 0
    for d in range(2):
        s0, s1 = h0.shard(d), h1.shard(d)
        assert int(s0.fr_ptr) == int(s1.fr_ptr)
        st = min(int(s0.fr_ptr), int(s0.cap))
        found += st
        assert np.array_equal(s0.fr_bufs[:st], s1.fr_bufs[:st])
        assert np.array_equal(s0.adm_bufs, s1.adm_bufs)
    assert found > 0, "vacuous"
    assert np.array_equal(np.asarray(i0.virgin_bits),
                          np.asarray(i1.virgin_bits))


# ---------------------------------------------------------------------------
# provenance sidecars: store round-trip, back-compat, quarantine
# ---------------------------------------------------------------------------


def test_provenance_sidecar_roundtrip_and_backcompat(tmp_path):
    store = CorpusStore(str(tmp_path / "c"))
    prov = dataset.make_provenance(b"AAAA", b"ABAA", "havoc", None)
    e = CorpusEntry(b"ABAA", parent="base", provenance=prov)
    assert store.put(e)
    old = CorpusEntry(b"OLD!")           # pre-learn sidecar: no field
    assert store.put(old)
    # strip the provenance key entirely (an OLD writer's sidecar)
    meta = json.loads(open(store.meta_path(old.md5)).read())
    meta.pop("provenance", None)
    with open(store.meta_path(old.md5), "w") as f:
        json.dump(meta, f)
    loaded = {x.md5: x for x in store.load()}
    assert loaded[e.md5].provenance == prov
    assert loaded[old.md5].provenance is None


def test_validator_accepts_and_bounds_provenance():
    import base64
    v = EntryValidator()

    def row(prov):
        return {"content_b64": base64.b64encode(b"hello").decode(),
                "meta": {"provenance": prov}}

    good = dataset.make_provenance(b"hello", b"hellp", "havoc",
                                   "havoc")
    entry, reason = v.validate(row(good))
    assert reason is None and entry.provenance == good
    for bad in (
            "not-a-dict",
            {"mutator": 7},
            {"mutator": "x" * 65},
            {"mutator": "havoc", "stage": 5},
            {"mutator": "havoc", "bitmap": "A" * 4096},
            {"mutator": "havoc", "bytes": -1},
            {"mutator": "havoc", "bytes": 10**6}):
        entry, reason = v.validate(row(bad))
        assert entry is None and reason == "schema:provenance", bad
    # absent field: pre-learn rows pass untouched
    entry, reason = v.validate(
        {"content_b64": base64.b64encode(b"hello").decode(),
         "meta": {}})
    assert reason is None and entry.provenance is None


def test_corpus_stats_provenance_line(tmp_path):
    from killerbeez_tpu.tools.corpus_tool import render_stats
    prov = dataset.make_provenance(b"\x00" * 8, b"\x00\xFF" * 4,
                                   "havoc", None)
    entries = [CorpusEntry(b"\x00\xFF" * 4, provenance=prov),
               CorpusEntry(b"plain")]
    out = render_stats(entries)
    assert "provenance" in out
    assert "1 labeled / 1 unlabeled" in out
    assert "top mutated positions" in out


# ---------------------------------------------------------------------------
# loop end-to-end: labels -> training -> events -> checkpoint/resume
# ---------------------------------------------------------------------------


def _learn_campaign(tmp_path, name, resume=False, tier=None):
    instr = instrumentation_factory(
        "jit_harness", '{"target": "cgc_like", "learn": 1}')
    mut = mutator_factory("havoc", '{"seed": 11}',
                          b"CG\x02\x04\x05Axxx")
    drv = driver_factory("file", None, instr, mut)
    tier = tier or LearnTier(train_interval_s=0.0, min_labels=8)
    fz = Fuzzer(drv, output_dir=str(tmp_path / name),
                batch_size=256, feedback=8,
                corpus_dir=str(tmp_path / name / "corpus"),
                resume=resume, learn=tier)
    return fz


def test_learn_e2e_trains_events_counters_resume(tmp_path):
    fz = _learn_campaign(tmp_path, "c")
    fz.run(8192)
    tier = fz.learn
    assert len(tier.labels) > 0 and tier.labels.positives > 0
    assert tier.version > 0 and tier.train_steps > 0
    reg = fz.telemetry.registry
    assert reg.counters["learn_train_steps"] == tier.train_steps
    assert reg.gauges["learn_model_version"] == tier.version
    evs = [json.loads(l) for l in
           open(tmp_path / "c" / "events.jsonl") if l.strip()]
    ups = [e for e in evs if e["type"] == "learn_update"]
    assert ups and ups[-1]["version"] == tier.version
    # provenance reached the sidecars
    store_dir = tmp_path / "c" / "corpus"
    provs = 0
    for n in os.listdir(store_dir):
        if not n.endswith(".json") or n == "campaign.json":
            continue
        try:
            d = json.loads(open(store_dir / n).read())
        except ValueError:
            continue
        provs += bool(isinstance(d, dict) and d.get("provenance"))
    assert provs > 0
    # --resume: the checkpointed model comes back and labels rebuild
    # from the provenance sidecars
    fz2 = _learn_campaign(tmp_path, "c", resume=True,
                          tier=LearnTier())
    assert fz2.learn.version == tier.version
    assert np.allclose(np.asarray(fz2.learn.params[4]),
                       np.asarray(tier.params[4]))
    assert len(fz2.learn.labels) > 0


def test_learn_counters_fold_through_merge():
    from killerbeez_tpu.telemetry.aggregate import merge
    a = {"counters": {"learn_train_steps": 8,
                      "learn_masks_applied": 3},
         "gauges": {"learn_model_version": 2,
                    "learn_label_count": 100}}
    b = {"counters": {"learn_train_steps": 5,
                      "learn_masks_applied": 1},
         "gauges": {"learn_model_version": 3,
                    "learn_label_count": 50}}
    m = merge([a, b])
    assert m["counters"]["learn_train_steps"] == 13
    assert m["counters"]["learn_masks_applied"] == 4
    assert m["gauges"]["learn_model_version"] == 3
    assert m["gauges"]["learn_label_count"] == 100


def test_kb_stats_learn_row():
    from killerbeez_tpu.tools.stats_tui import render
    snap = {"counters": {"execs": 1000, "learn_train_steps": 24,
                         "learn_masks_applied": 6},
            "gauges": {"learn_model_version": 3,
                       "learn_label_count": 420},
            "rates": {}, "derived": {}, "elapsed": 1.0}
    out = render(snap)
    assert "learn" in out and "model v3" in out
    assert "420 labels" in out and "24 train steps" in out
    assert "6 masks applied" in out
    # row absent without the tier
    out2 = render({"counters": {"execs": 1}, "gauges": {},
                   "rates": {}, "derived": {}, "elapsed": 1.0})
    assert "model v" not in out2


def test_learn_update_event_type_registered():
    from killerbeez_tpu.telemetry.events import EVENT_TYPES
    assert "learn_update" in EVENT_TYPES


# ---------------------------------------------------------------------------
# fixedform_vm: the bench family's honesty certificate
# ---------------------------------------------------------------------------


def test_fixedform_family_certificate():
    """The bench gate's uplift claim rests on the padding being
    PROVABLY inert: the dataflow layer's branch dependency union
    must name only the documented live offsets — no branch anywhere
    in the program reads a padding byte (store index 81 is live for
    the planted bug's crash location but gates no branch)."""
    from killerbeez_tpu.analysis import analyze_dataflow
    from killerbeez_tpu.models import targets_cgc
    from killerbeez_tpu.models.targets import get_target

    prog = get_target("fixedform_vm")
    df = analyze_dataflow(prog)
    deps = set()
    for br in df.branches:
        deps |= set(br.deps or [])
    live = {0, 1, 8, 16, 32, 64, 65, 72, 80} | set(range(24, 32))
    assert deps == live
    # seed exits clean; crash reproducer crashes
    instr = instrumentation_factory("jit_harness",
                                    '{"target": "fixedform_vm"}')
    instr.enable(targets_cgc.fixedform_vm_seed())
    assert instr.last_status == 0
    instr2 = instrumentation_factory("jit_harness",
                                     '{"target": "fixedform_vm"}')
    instr2.enable(targets_cgc.fixedform_vm_crash())
    assert instr2.last_status == 2      # FUZZ_CRASH


def test_learned_mask_concentrates_on_live_offsets():
    """Train the tier on fixedform-style labels (admissions mutate
    live offsets, rejects/background the padding): the quantized
    mask must keep the live offsets and drop most padding — the
    mechanism behind the bench gate's uplift."""
    from killerbeez_tpu.models import targets_cgc
    seed = targets_cgc.fixedform_vm_seed()
    live = sorted({0, 1, 8, 16, 32, 64, 65, 72, 80}
                  | set(range(24, 32)))
    tier = LearnTier(train_interval_s=0.0, min_labels=16,
                     steps_per_round=60)
    rng = np.random.default_rng(3)
    for i in range(120):
        pos = rng.choice(live, size=2, replace=False)
        child = bytearray(seed)
        for p in pos:
            child[p] ^= int(rng.integers(1, 256))
        tier.note_admission("base", seed, bytes(child), "havoc")
    tier.train_round()
    assert tier.version >= 1
    mask = tier.mask_for(seed)
    kept = set(np.flatnonzero(mask[:len(seed)]).tolist())
    # the tiny windowed MLP generalizes, it does not memorize: most
    # live offsets survive and most padding drops — the density
    # shift the bench gate measures, not an exact set recovery
    assert len(set(live) & kept) >= len(live) * 3 // 4
    assert len(kept) < len(seed) * 3 // 4
