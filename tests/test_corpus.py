"""Corpus subsystem tests (killerbeez_tpu/corpus/): store round-trip
and crash-safe writes, scheduler policies (bandit parity with the
historical in-loop behavior, rare-edge rarity preference, rr cycling),
kill/--resume continuation, and manager-mediated corpus sync with
coverage-hash dedup."""

import base64
import json
import os
import random
import urllib.request

import pytest

from killerbeez_tpu.corpus import (
    Arm, BanditScheduler, CorpusEntry, CorpusStore, CorpusSync,
    RareEdgeScheduler, RoundRobinScheduler, make_scheduler,
)
from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.fuzzer.cli import main as cli_main
from killerbeez_tpu.fuzzer.loop import Fuzzer
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.mutators.factory import mutator_factory

SEED = b"CG\x02\x04\x05\x41xx"


# -- store -------------------------------------------------------------


def test_store_roundtrip(tmp_path):
    store = CorpusStore(str(tmp_path / "c"))
    e1 = CorpusEntry(b"AAAA", seq=store.next_seq(), sig=[5, 9, 2],
                     parent="base", selections=1.5, finds=0.25)
    e2 = CorpusEntry(b"BBBBBB", seq=store.next_seq(), parent=e1.md5,
                     source="sync")
    assert store.put(e1) and store.put(e2)
    assert not store.put(CorpusEntry(b"AAAA"))   # md5 dedup
    assert len(store) == 2

    loaded = CorpusStore(str(tmp_path / "c")).load()
    assert [e.md5 for e in loaded] == [e1.md5, e2.md5]  # seq order
    l1 = loaded[0]
    assert l1.buf == b"AAAA"
    assert l1.sig == [2, 5, 9]                   # sorted, deduped
    assert l1.selections == 1.5 and l1.finds == 0.25
    assert l1.parent == "base" and l1.cov_hash.startswith("sig:")
    assert loaded[1].source == "sync"
    assert loaded[1].cov_hash.startswith("md5:")  # unsigned fallback


def test_store_survives_torn_writes(tmp_path):
    """Crash-safety: leftover .tmp files and a torn sidecar must not
    lose the store — the entry bytes are the artifact."""
    store = CorpusStore(str(tmp_path / "c"))
    e = CorpusEntry(b"DATA", seq=0, sig=[1])
    store.put(e)
    # simulate a crash mid-write: stray tmp + corrupt sidecar
    (tmp_path / "c" / "deadbeef.tmp").write_bytes(b"partial")
    (tmp_path / "c" / (e.md5 + ".json")).write_text('{"md5": trunc')
    loaded = store.load()
    assert len(loaded) == 1
    assert loaded[0].buf == b"DATA"              # bytes survive
    assert loaded[0].sig is None                 # metadata degraded


def test_store_state_roundtrip(tmp_path):
    store = CorpusStore(str(tmp_path / "c"))
    store.save_state({"counters": {"execs": 42}})
    assert store.load_state()["counters"]["execs"] == 42
    store.save_component_state("mutator", '{"iteration": 7}')
    assert json.loads(store.load_component_state("mutator")) \
        == {"iteration": 7}
    assert store.load_component_state("instrumentation") is None


# -- bandit parity -----------------------------------------------------


def _reference_bandit_pick(corpus, base_stats, base_seed, rng):
    """The pre-extraction in-loop selection (fuzzer/loop.py history):
    greedy optimistic bandit + AFL-style splice, verbatim."""
    best, best_score = None, 0.0
    if base_seed is not None:
        best_score = (base_stats[1] + 1.0) / (base_stats[0] + 1.0)
    for i, (buf, sel, finds) in enumerate(corpus):
        score = (finds + 1.0) / (sel + 1.0)
        if score >= best_score:
            best, best_score = i, score
    if best is None:
        return None, base_seed
    cand = corpus[best][0]
    if len(corpus) >= 2 and rng.random() < 0.5:
        partner = rng.choice(
            [e[0] for j, e in enumerate(corpus) if j != best])
        n = min(len(cand), len(partner))
        fd = next((i for i in range(n) if cand[i] != partner[i]), None)
        if fd is not None:
            ld = next(i for i in range(n - 1, -1, -1)
                      if cand[i] != partner[i])
            if ld > fd + 1:
                k = rng.randrange(fd + 1, ld)
                cand = cand[:k] + partner[k:]
    return best, cand


def test_bandit_parity_with_historical_inloop_behavior():
    """--schedule bandit must reproduce the old rotation decisions:
    drive the extracted scheduler and a verbatim copy of the
    pre-extraction algorithm through the same scripted episode (same
    admissions, finds, periods, RNG seed) and require the SAME arm
    index and candidate bytes at every rotation."""
    sched = BanditScheduler()
    sched.base_seed = b"BASE_SEED_0"
    ref_corpus, ref_stats = [], [0.0, 0.0]
    ref_rng = random.Random(0x6b62)     # the loop's historical seed

    script_rng = random.Random(1)
    ref_active = None                   # arm list obj or None
    for step in range(200):
        # random admissions (edge-novel findings) with random credit
        if script_rng.random() < 0.4:
            buf = bytes(script_rng.randrange(256) for _ in range(12))
            sched.admit(Arm(buf))
            sched.credit_find(sched.arms[ref_active]
                              if ref_active is not None else None)
            ref_corpus.append([buf, 0, 0])
            if ref_active is None:
                ref_stats[1] += 1
            else:
                ref_corpus[ref_active][2] += 1
        # period close (the old _credit_period with feedback=8)
        g = 0.8 ** 8
        ref_stats[0] *= g
        ref_stats[1] *= g
        for e in ref_corpus:
            e[1] *= g
            e[2] *= g
        active_entry = (sched.arms[ref_active]
                        if ref_active is not None else None)
        sched.credit_period(active_entry, 8)
        if ref_active is None:
            ref_stats[0] += 1
        else:
            ref_corpus[ref_active][1] += 1
        # rotation
        got_best, got_cand = sched.select()
        ref_best, ref_cand = _reference_bandit_pick(
            ref_corpus, ref_stats, b"BASE_SEED_0", ref_rng)
        assert got_best == ref_best, f"arm diverged at step {step}"
        assert got_cand == ref_cand, f"splice diverged at step {step}"
        ref_active = ref_best
        # stats must stay numerically identical too
        assert ref_stats == pytest.approx(sched.base_stats)
        assert [list(a) for a in sched.arms] == \
            [[b, pytest.approx(s), pytest.approx(f)]
             for b, s, f in ref_corpus]


def test_bandit_cap_evicts_oldest():
    sched = BanditScheduler(cap=3)
    arms = [Arm(bytes([i]) * 4) for i in range(5)]
    evicted = [sched.admit(a) for a in arms]
    assert len(sched.arms) == 3
    assert sched.arms == arms[2:]
    assert evicted[3] is arms[0] and evicted[4] is arms[1]


# -- rr / rare-edge policies -------------------------------------------


def test_rr_cycles_base_and_arms():
    sched = RoundRobinScheduler()
    sched.base_seed = b"BASE"
    a1, a2 = Arm(b"ONE1"), Arm(b"TWO2")
    sched.admit(a1)
    sched.admit(a2)
    picks = [sched.select() for _ in range(6)]
    assert picks == [(None, b"BASE"), (0, b"ONE1"), (1, b"TWO2")] * 2


def test_rare_edge_prefers_rarest_signature():
    sched = RareEdgeScheduler()
    sched.base_seed = b"BASE"
    common = Arm(b"AAAA", sig=[1, 2])
    also_common = Arm(b"BBBB", sig=[1, 2, 3])
    rare = Arm(b"CCCC", sig=[3, 99])    # 99 hit by this entry only
    for a in (common, also_common, rare):
        sched.admit(a)
    assert sched.edge_hits == {1: 2, 2: 2, 3: 2, 99: 1}
    best, cand = sched.select()
    assert sched.arms[best] is rare and cand == b"CCCC"
    # equal rarity: the least-selected arm gets the turn, newest
    # breaks remaining ties
    other_rare = Arm(b"DDDD", sig=[98])     # also a singleton edge
    sched.admit(other_rare)
    rare[1] += 10                           # heavily selected
    best, _ = sched.select()
    assert sched.arms[best] is other_rare
    assert sched.favored_count() >= 1


def test_rare_edge_unsigned_probe_once():
    sched = RareEdgeScheduler()
    sched.base_seed = b"BASE"
    blind = Arm(b"XXXX")                # no signature available
    sched.admit(blind)
    best, _ = sched.select()
    assert sched.arms[best] is blind    # probed once
    blind[1] += 1                       # now selected
    picks = {sched.select()[0] for _ in range(8)}
    # deprioritized: budget splits with the base seed
    assert None in picks


def test_rare_edge_drop_releases_edge_counts():
    """Arms dropped from rotation (too-wide findings) must release
    their edge_hits, or surviving arms' rarity reads stale."""
    sched = RareEdgeScheduler()
    wide = Arm(b"W" * 64, sig=[1, 7])
    small = Arm(b"SSSS", sig=[7])
    sched.admit(wide)
    sched.admit(small)
    assert sched.edge_hits == {1: 1, 7: 2}
    sched.drop(0)                       # the wide arm
    assert sched.edge_hits == {7: 1}    # counts released
    # eviction releases too
    capped = RareEdgeScheduler(cap=1)
    a, b = Arm(b"AAAA", sig=[5]), Arm(b"BBBB", sig=[6])
    capped.admit(a)
    capped.admit(b)                     # evicts a
    assert capped.edge_hits == {6: 1}


def test_make_scheduler_names():
    for name in ("bandit", "rare-edge", "rr"):
        assert make_scheduler(name).name == name
    with pytest.raises(ValueError, match="unknown scheduler"):
        make_scheduler("nope")


# -- loop integration: store write-through + resume --------------------


def _make_fuzzer(tmp_path, corpus_dir=None, resume=False,
                 scheduler=None, seed_n=11, feedback=2, sync=None):
    instr = instrumentation_factory(
        "jit_harness", '{"target": "cgc_like", "novelty": "throughput"}')
    mut = mutator_factory("havoc", json.dumps({"seed": seed_n}), SEED)
    drv = driver_factory("file", None, instr, mut)
    return Fuzzer(drv, output_dir=str(tmp_path / "out"),
                  batch_size=256, feedback=feedback,
                  corpus_dir=corpus_dir, resume=resume,
                  scheduler=scheduler, sync=sync,
                  persist_interval=0.0)


def test_loop_writes_store_and_resumes_in_process(tmp_path):
    """The resume acceptance gate: a campaign's corpus, bandit stats
    and lifetime counters survive a kill and continue."""
    cdir = str(tmp_path / "corpus")
    fz = _make_fuzzer(tmp_path, corpus_dir=cdir)
    fz.run(2048)
    arms = len(fz.scheduler.arms)
    seen = set(fz._seen["new_paths"])
    execs = fz.stats.iterations
    paths = fz.stats.new_paths
    base_stats = list(fz.scheduler.base_stats)
    rotations = fz.scheduler.rotations
    assert arms > 0 and execs == 2048
    # store holds every rotation arm (write-through at admission)
    stored = {e.md5 for e in CorpusStore(cdir).load()}
    assert {a.md5 for a in fz.scheduler.arms} <= stored

    # "kill": drop the object, rebuild from disk alone
    fz2 = _make_fuzzer(tmp_path, corpus_dir=cdir, resume=True)
    assert len(fz2.scheduler.arms) == arms          # same arm count
    assert fz2._seen["new_paths"] >= seen           # no findings lost
    assert fz2.stats.iterations == execs            # counters continue
    assert fz2.stats.new_paths == paths
    assert fz2.scheduler.rotations == rotations
    assert fz2.scheduler.base_stats == \
        pytest.approx(base_stats)                   # bandit stats
    assert fz2.scheduler.base_seed == fz.scheduler.base_seed
    # mutator walk position restored -> no candidate replay
    assert fz2.driver.mutator.get_current_iteration() == 2048

    fz2.run(512)                                    # -n is per-run
    assert fz2.stats.iterations == execs + 512
    # replayed known paths are not re-recorded as new findings
    assert fz2.stats.new_paths >= paths


def test_cli_resume_smoke(tmp_path):
    """Fast tier-1 guard for the CLI resume path: --corpus-dir run,
    then --resume continues counters and corpus (fuzzer_stats shows
    the cumulative totals)."""
    from killerbeez_tpu.telemetry import parse_fuzzer_stats
    seed_path = tmp_path / "seed"
    seed_path.write_bytes(SEED)
    out = tmp_path / "out"
    common = ["file", "jit_harness", "havoc",
              "-i", '{"target": "cgc_like", "novelty": "throughput"}',
              "-m", '{"seed": 11}', "-sf", str(seed_path),
              "-o", str(out), "-b", "256", "-fb", "2"]
    assert cli_main(common + ["-n", "1024",
                              "--corpus-dir", str(out / "corpus")]) == 0
    n_entries = len(CorpusStore(str(out / "corpus")).load())
    assert n_entries > 0
    assert cli_main(common + ["-n", "512", "--resume"]) == 0
    fs = parse_fuzzer_stats(str(out / "fuzzer_stats"))
    assert int(fs["execs_done"]) == 1536            # 1024 + 512
    assert int(fs["corpus_count"]) >= n_entries
    assert int(fs["corpus_arms"]) > 0


def test_interval_persist_snapshots_live_run_seconds(tmp_path):
    """A hard kill never reaches run_ended(): the interval persist
    must snapshot LIVE active time, or a resumed campaign divides
    restored execs by ~zero and reports an absurd lifetime rate."""
    import time as _time
    fz = _make_fuzzer(tmp_path, corpus_dir=str(tmp_path / "c"))
    reg = fz.telemetry.registry
    reg.run_started()                   # mid-run, never ended
    _time.sleep(0.05)
    fz._persist_campaign()
    st = CorpusStore(str(tmp_path / "c")).load_state()
    assert st["counters"]["run_seconds"] >= 0.05


def test_resume_requires_corpus_dir(tmp_path):
    with pytest.raises(ValueError, match="corpus_dir"):
        _make_fuzzer(tmp_path, resume=True)


def test_scheduler_choice_changes_policy_not_findings(tmp_path):
    """--schedule rr on the same candidate stream still fuzzes and
    admits the same first-period findings (policy only changes
    SELECTION; admission and triage are scheduler-independent)."""
    fz = _make_fuzzer(tmp_path, scheduler="rr")
    stats = fz.run(2048)
    assert stats.new_paths > 0
    assert fz.scheduler.name == "rr"
    assert fz.scheduler.rotations > 0
    assert len(fz.scheduler.arms) > 0


# -- corpus gauges -----------------------------------------------------


def test_corpus_gauges_split(tmp_path):
    """The misleading corpus_size gauge is gone: corpus_seen counts
    distinct recorded new-path inputs, corpus_arms the rotation
    corpus; fuzzer_stats carries both (corpus_count keeps the AFL
    wire name)."""
    fz = _make_fuzzer(tmp_path, corpus_dir=str(tmp_path / "c"))
    fz.run(2048)
    g = fz.telemetry.registry.gauges
    assert "corpus_size" not in g
    assert g["corpus_seen"] == len(fz._seen["new_paths"])
    assert g["corpus_arms"] == len(fz.scheduler.arms)
    assert "corpus_favored" in g
    from killerbeez_tpu.telemetry.sink import write_fuzzer_stats
    from killerbeez_tpu.telemetry import parse_fuzzer_stats
    path = str(tmp_path / "fs")
    write_fuzzer_stats(path, fz.telemetry.snapshot())
    fs = parse_fuzzer_stats(path)
    assert int(fs["corpus_count"]) == int(g["corpus_seen"])
    assert int(fs["corpus_arms"]) == int(g["corpus_arms"])


# -- manager corpus sync -----------------------------------------------


@pytest.fixture
def server():
    from killerbeez_tpu.manager import ManagerServer
    s = ManagerServer(port=0)
    s.start()
    yield s
    s.stop()


def _post(server, path, payload):
    url = f"http://127.0.0.1:{server.port}{path}"
    r = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def test_corpus_endpoint_dedups_by_coverage_hash(server):
    """Two workers, one shared finding: stored ONCE (the acceptance
    dedup gate) — different md5s, same coverage signature."""
    def entry(worker, md5, content):
        return {"worker": worker, "md5": md5,
                "cov_hash": "sig:deadbeef",
                "content_b64": base64.b64encode(content).decode(),
                "meta": {"seq": 0}}

    code, r1 = _post(server, "/api/corpus/j1", entry("w1", "m1", b"A"))
    assert code == 201 and r1["new"] is True
    code, r2 = _post(server, "/api/corpus/j1", entry("w2", "m2", b"B"))
    assert code == 200 and r2["new"] is False       # dedup
    assert r2["id"] == r1["id"]
    # the row is w1's; w2 pulling with exclude=w2 still sees it,
    # w1 pulling with exclude=w1 does not (it authored it)
    db = server.db
    assert len(db.get_corpus_entries("j1", 0, "w2")) == 1
    assert len(db.get_corpus_entries("j1", 0, "w1")) == 0
    # a different campaign is a separate namespace
    code, r3 = _post(server, "/api/corpus/j2", entry("w1", "m1", b"A"))
    assert r3["new"] is True


def test_two_worker_sync_exchanges_frontier(server, tmp_path):
    """Fleet e2e: worker 2's scheduler ends up rotating through
    worker 1's findings (pulled via /api/corpus), and shared
    frontiers are stored once server-side."""
    url = f"http://127.0.0.1:{server.port}"

    def worker(name, seed_n):
        sync = CorpusSync(url, "campX", worker=name, interval_s=0.0)
        return _make_fuzzer(tmp_path / name,
                            corpus_dir=str(tmp_path / name / "c"),
                            seed_n=seed_n, sync=sync)

    f1 = worker("w1", 11)
    f1.run(1024)
    assert f1.sync.pushed_n > 0
    f2 = worker("w2", 22)
    f2.run(1024)
    assert f2.sync.pulled_n > 0
    sources = [a.source for a in f2.scheduler.arms]
    assert "sync" in sources            # peer entries joined rotation
    # pulled entries persist in w2's local store
    stored = CorpusStore(str(tmp_path / "w2" / "c")).load()
    assert any(e.source == "sync" for e in stored)
    # server kept one row per coverage hash
    rows = server.db.get_corpus_entries("campX", 0)
    hashes = [r["cov_hash"] for r in rows]
    assert len(hashes) == len(set(hashes))
    c = f2.telemetry.registry.counters
    assert c.get("corpus_synced_in", 0) == f2.sync.pulled_n
    assert c.get("corpus_synced_out", 0) == f2.sync.pushed_n


def test_resumed_worker_does_not_readmit_pulled_entries(server,
                                                        tmp_path):
    """Restarting a resumed syncing worker must not re-admit
    previously-pulled peer entries: the fresh CorpusSync's cursor is
    0, but store-known md5s / cov_hashes gate the pull loop."""
    url = f"http://127.0.0.1:{server.port}"
    f1 = _make_fuzzer(tmp_path / "w1",
                      corpus_dir=str(tmp_path / "w1" / "c"),
                      sync=CorpusSync(url, "campR", worker="w1",
                                      interval_s=0.0))
    f1.run(1024)
    f2 = _make_fuzzer(tmp_path / "w2",
                      corpus_dir=str(tmp_path / "w2" / "c"),
                      seed_n=22,
                      sync=CorpusSync(url, "campR", worker="w2",
                                      interval_s=0.0))
    f2.run(1024)
    assert f2.sync.pulled_n > 0
    arms_before = len(f2.scheduler.arms)
    # restart worker 2: fresh sync client, resumed campaign
    f2b = _make_fuzzer(tmp_path / "w2",
                       corpus_dir=str(tmp_path / "w2" / "c"),
                       seed_n=22, resume=True,
                       sync=CorpusSync(url, "campR", worker="w2",
                                       interval_s=1e9))
    assert len(f2b.scheduler.arms) == arms_before
    synced_in = f2b.telemetry.registry.counters["corpus_synced_in"]
    assert f2b.sync.maybe_sync(f2b, force=True)
    assert len(f2b.scheduler.arms) == arms_before   # no re-admission
    assert f2b.sync.pulled_n == 0
    assert f2b.telemetry.registry.counters["corpus_synced_in"] \
        == synced_in


def test_sync_survives_dead_manager(tmp_path, monkeypatch):
    """A dead manager degrades to warnings AND costs one transport
    failure per sync ROUND, not per entry: the round aborts on the
    first failed push and requeues the rest for the next round."""
    import killerbeez_tpu.manager.worker as w
    calls = {"n": 0}
    orig = w._request_retry

    def counting(url, payload=None, method="POST", **kw):
        calls["n"] += 1
        return orig(url, payload, method, **kw)

    monkeypatch.setattr(w, "_request_retry", counting)
    sync = CorpusSync("http://127.0.0.1:1", "c", worker="w",
                      interval_s=0.0, attempts=1)
    fz = _make_fuzzer(tmp_path, sync=sync)
    stats = fz.run(512)
    assert stats.iterations == 512
    assert sync.pushed_n == 0 and sync.pulled_n == 0
    # entries admitted during the run are requeued, not lost
    assert len(sync._pending) == len(fz.scheduler.arms) > 0
    # rounds that had nothing to push cost zero requests; rounds with
    # entries cost exactly ONE failed push (abort + requeue) — far
    # fewer total requests than entries*rounds
    assert calls["n"] <= 2 * (512 // 256 + 1)


def test_sync_counters_survive_resume(tmp_path):
    """corpus_synced_in/out are per-round deltas onto the registry:
    a resumed campaign's restored cumulative totals keep counting up
    instead of snapping back to process-local values."""
    sync = CorpusSync("http://127.0.0.1:1", "c", worker="w",
                      interval_s=1e9, attempts=1)   # rounds gated off
    fz = _make_fuzzer(tmp_path, sync=sync)
    fz.telemetry.registry.counters["corpus_synced_in"] = 100.0
    assert sync.maybe_sync(fz, force=True)          # round runs, no peers
    assert fz.telemetry.registry.counters["corpus_synced_in"] == 100.0


# -- kb-corpus tool ----------------------------------------------------


def test_kb_corpus_ls_stats_compact(tmp_path, capsys):
    from killerbeez_tpu.tools.corpus_tool import main as kbc
    cdir = str(tmp_path / "c")
    store = CorpusStore(cdir)
    # b's edges are a subset of a's -> compact removes b; c unsigned
    store.put(CorpusEntry(b"AAAA", seq=0, sig=[1, 2, 3]))
    b = CorpusEntry(b"BBBB", seq=1, sig=[2])
    store.put(b)
    store.put(CorpusEntry(b"CCCC", seq=2))
    assert kbc(["ls", cdir]) == 0
    out = capsys.readouterr().out
    assert b.md5 in out and "parent" in out
    assert kbc(["stats", cdir]) == 0
    out = capsys.readouterr().out
    assert "entries        : 3 (2 signed, 1 unsigned)" in out
    assert "distinct edges : 3" in out
    # dry run removes nothing
    assert kbc(["compact", cdir, "--dry-run"]) == 0
    assert capsys.readouterr().out.strip() == b.md5
    assert len(store.load()) == 3
    # real compaction drops the covered entry, keeps the unsigned one
    assert kbc(["compact", cdir]) == 0
    kept = {e.md5 for e in store.load()}
    assert b.md5 not in kept and len(kept) == 2


def test_explicit_accumulate_degrade_warns(capsys):
    """ADVICE r5: an explicit -K silently degraded to a divisor of
    -fb; the constraint (superbatches may not stride a rotation
    boundary) must be named at WARNING."""
    from killerbeez_tpu.fuzzer.loop import Fuzzer

    class _Drv:
        supports_batch = True
        mutator = None
        instrumentation = None
        stage_timer = None

        def supports_fused_multi(self):
            return True

    fz = Fuzzer(_Drv(), write_findings=False, accumulate=5,
                feedback=8, telemetry=False)
    assert fz._resolve_accumulate() == 4    # largest K<=5 dividing 8
    err = capsys.readouterr().err
    assert "degraded" in err and "-fb" in err
    # the warning names the explicit K and fires once
    assert "5" in err
    fz._resolve_accumulate()
    assert "degraded" not in capsys.readouterr().err
    # auto K (accumulate=0) degrades silently — nothing explicit to
    # contradict
    fz2 = Fuzzer(_Drv(), write_findings=False, accumulate=0,
                 feedback=3, telemetry=False)
    capsys.readouterr()
    assert fz2._resolve_accumulate() == 3   # largest divisor of 3 <= 8
    assert "degraded" not in capsys.readouterr().err
