"""Pallas VM kernel parity (ops/vm_kernel.py): the VMEM-resident
engine must be bit-identical to the XLA while_loop engine across
statuses, exit codes, static-edge counts, step counts and path
hashes.  Tests run the kernel in interpreter mode (CI has no TPU);
the same comparison passes compiled on a real chip (see bench)."""

import jax.numpy as jnp
import numpy as np
import pytest

# ~2-4 min of CPU-mesh/interpret-mode work: nightly lane only
pytestmark = pytest.mark.slow

from killerbeez_tpu.models import targets, targets_cgc
from killerbeez_tpu.models.vm import _run_batch_impl
from killerbeez_tpu.ops.vm_kernel import LANE_TILE, run_batch_pallas

FIELDS = ("status", "exit_code", "counts", "steps", "path_hash")


def _mutant_batch(prog_name, rng, B, L):
    seed_fn = targets_cgc.VM_SEEDS.get(prog_name)
    seed = seed_fn[0]() if seed_fn else b"ABC@"
    inputs = np.zeros((B, L), np.uint8)
    inputs[:, :len(seed)] = np.frombuffer(seed, np.uint8)
    mask = rng.random((B, L)) < 0.2
    inputs = np.where(mask, rng.integers(0, 256, (B, L)),
                      inputs).astype(np.uint8)
    lengths = rng.integers(1, L + 1, B).astype(np.int32)
    return inputs, lengths


@pytest.mark.parametrize("name", ["test", "tlvstack_vm", "imgparse_vm",
                                  "rledec_vm", "hang", "libtest"])
def test_pallas_matches_xla_engine(name, rng):
    prog = targets.get_target(name)
    B, L = LANE_TILE, 32
    inputs, lengths = _mutant_batch(name, rng, B, L)
    args = (jnp.asarray(prog.instrs), jnp.asarray(prog.edge_table),
            jnp.asarray(inputs), jnp.asarray(lengths),
            prog.mem_size, prog.max_steps, prog.n_edges)
    ref = _run_batch_impl(*args, False)
    out = run_batch_pallas(*args, interpret=True)
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), np.asarray(getattr(out, f)),
            err_msg=f"{name}: {f} diverged")


def test_pallas_rejects_unaligned_batch():
    prog = targets.get_target("test")
    with pytest.raises(ValueError):
        run_batch_pallas(jnp.asarray(prog.instrs),
                         jnp.asarray(prog.edge_table),
                         jnp.zeros((100, 8), jnp.uint8),
                         jnp.full((100,), 4, jnp.int32),
                         prog.mem_size, prog.max_steps, prog.n_edges,
                         interpret=True)


def test_jit_harness_pallas_engine(tmp_path):
    """The engine option plugs into the full instrumentation path and
    pads non-aligned batches transparently."""
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    xla = instrumentation_factory(
        "jit_harness", '{"target": "test", "novelty": "throughput"}')
    pls = instrumentation_factory(
        "jit_harness", '{"target": "test", "novelty": "throughput", '
        '"engine": "pallas"}')
    rng = np.random.default_rng(7)
    B, L = 96, 8                                # not LANE_TILE-aligned
    inputs, lengths = _mutant_batch("test", rng, B, L)
    # interpret-mode monkeypatch: CI has no TPU to compile for
    import killerbeez_tpu.ops.vm_kernel as vk
    orig = vk.run_batch_pallas
    vk_run = lambda *a, **k: orig(  # noqa: E731
        *a, **{**k, "interpret": True})
    import killerbeez_tpu.instrumentation.jit_harness as jh
    jh._fused_step.clear_cache()
    try:
        vk.run_batch_pallas = vk_run
        r_x = xla.run_batch(inputs, lengths)
        r_p = pls.run_batch(inputs, lengths)
    finally:
        vk.run_batch_pallas = orig
        jh._fused_step.clear_cache()
    np.testing.assert_array_equal(r_x.statuses, r_p.statuses)
    np.testing.assert_array_equal(r_x.new_paths, r_p.new_paths)


def test_fused_mutate_execute_parity(rng):
    """fuzz_batch_pallas runs havoc INSIDE the kernel; with the same
    PRNG words it must reproduce the havoc_at -> VM pipeline
    bit-for-bit: mutant bytes, lengths, and every execution field."""
    import jax
    from killerbeez_tpu.ops.mutate_core import havoc_at
    from killerbeez_tpu.ops.vm_kernel import (
        fuzz_batch_pallas, havoc_words,
    )
    prog = targets.get_target("tlvstack_vm")
    B, L = LANE_TILE, 32
    seed = targets_cgc.VM_SEEDS["tlvstack_vm"][0]()
    seed_buf = np.zeros(L, np.uint8)
    seed_buf[:len(seed)] = np.frombuffer(seed, np.uint8)
    seed_j = jnp.asarray(seed_buf)
    seed_len = jnp.int32(len(seed))
    ins = jnp.asarray(prog.instrs)
    tbl = jnp.asarray(prog.edge_table)

    key = jax.random.fold_in(jax.random.key(0), 3)
    words = havoc_words(key, B)
    res, bufs, lens = fuzz_batch_pallas(
        ins, tbl, seed_j, seed_len, words, prog.mem_size,
        prog.max_steps, prog.n_edges, interpret=True)

    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(B, dtype=jnp.uint32))
    rbufs, rlens = jax.vmap(
        lambda k: havoc_at(seed_j, seed_len, k, stack_pow2=4))(keys)
    ref = _run_batch_impl(ins, tbl, rbufs, rlens, prog.mem_size,
                          prog.max_steps, prog.n_edges, False)
    np.testing.assert_array_equal(np.asarray(rbufs), np.asarray(bufs))
    np.testing.assert_array_equal(np.asarray(rlens), np.asarray(lens))
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), np.asarray(getattr(res, f)),
            err_msg=f"fused: {f} diverged")


def test_skip_mask_suppresses_lanes(rng):
    """run_batch_pallas(skip=...): skipped lanes report FUZZ_NONE with
    zero counts/steps; unskipped lanes are bit-identical to a no-skip
    run (the phase-2 half of two-phase scheduling)."""
    from killerbeez_tpu import FUZZ_NONE
    from killerbeez_tpu.ops.vm_kernel import run_batch_pallas as rbp
    prog = targets.get_target("tlvstack_vm")
    B, L = LANE_TILE, 32
    inputs, lengths = _mutant_batch("tlvstack_vm", rng, B, L)
    args = (jnp.asarray(prog.instrs), jnp.asarray(prog.edge_table),
            jnp.asarray(inputs), jnp.asarray(lengths),
            prog.mem_size, prog.max_steps, prog.n_edges)
    skip = (np.arange(B) % 2).astype(np.int32)
    full = rbp(*args, interpret=True)
    part = rbp(*args, interpret=True, skip=jnp.asarray(skip))
    sk = skip.astype(bool)
    assert (np.asarray(part.status)[sk] == FUZZ_NONE).all()
    assert (np.asarray(part.counts)[sk] == 0).all()
    assert (np.asarray(part.steps)[sk] == 0).all()
    for f in FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(full, f))[~sk],
            np.asarray(getattr(part, f))[~sk],
            err_msg=f"unskipped lanes: {f} diverged")


def test_two_phase_matches_single_phase(rng):
    """fuzz_batch_pallas_2phase must be bit-identical to the
    single-phase kernel for every phase1 budget (finished lanes are
    final at K; survivors re-run deterministically)."""
    import jax
    from killerbeez_tpu.ops.vm_kernel import (
        fuzz_batch_pallas, fuzz_batch_pallas_2phase, havoc_words,
    )
    prog = targets.get_target("tlvstack_vm")
    B, L = LANE_TILE, 32
    seed = targets_cgc.VM_SEEDS["tlvstack_vm"][0]()
    seed_buf = np.zeros(L, np.uint8)
    seed_buf[:len(seed)] = np.frombuffer(seed, np.uint8)
    words = havoc_words(jax.random.fold_in(jax.random.key(0), 11), B)
    base_args = (jnp.asarray(prog.instrs), jnp.asarray(prog.edge_table),
                 jnp.asarray(seed_buf), jnp.int32(len(seed)), words,
                 prog.mem_size, prog.max_steps, prog.n_edges)
    ref, rbufs, rlens = fuzz_batch_pallas(*base_args, interpret=True)
    for k in (8, 64, prog.max_steps):
        out, obufs, olens = fuzz_batch_pallas_2phase(
            *base_args, phase1_steps=k, interpret=True)
        np.testing.assert_array_equal(np.asarray(rbufs),
                                      np.asarray(obufs))
        np.testing.assert_array_equal(np.asarray(rlens),
                                      np.asarray(olens))
        for f in FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, f)), np.asarray(getattr(out, f)),
                err_msg=f"phase1_steps={k}: {f} diverged")
