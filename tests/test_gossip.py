"""Partition-tolerant fleet tier, part 1 (killerbeez_tpu/corpus/
gossip.py + quarantine.py, manager durability): entry-validator and
peer-ban units, the gossip sidecar's cursor API, hub-free peer
exchange, the manager's WAL/locked-retry/degraded read-only mode and
the write-ahead admission journal's SIGKILL-equivalent replay.

The fleet-scale convergence gates live in test_fleet_chaos.py."""

import base64
import json
import os
import random
import urllib.request

import pytest

from killerbeez_tpu.corpus import (
    CorpusEntry, CorpusStore, EntryValidator, GossipSidecar,
    GossipSync, PeerBans, QuarantineStore,
)
from killerbeez_tpu.corpus.store import coverage_hash
from killerbeez_tpu.manager.api import ManagerServer
from killerbeez_tpu.manager.db import ManagerDB, ManagerWriteError
from killerbeez_tpu.resilience import chaos
from killerbeez_tpu.resilience.fleetsim import SimWorker
from killerbeez_tpu.utils.fileio import md5_hex


@pytest.fixture(autouse=True)
def _no_chaos():
    yield
    chaos.configure(None)


def _row(buf: bytes, sig=None, **over):
    sig = sorted(sig or [])
    meta = {"sig": sig or None, "md5": md5_hex(buf),
            "cov_hash": coverage_hash(sig or None, buf),
            "seq": 0, "source": "local"}
    row = {"worker": "w", "md5": md5_hex(buf),
           "cov_hash": coverage_hash(sig or None, buf),
           "content_b64": base64.b64encode(buf).decode(),
           "meta": meta}
    row.update(over)
    return row


# -- validator units ----------------------------------------------------


def test_validator_accepts_honest_row():
    v = EntryValidator()
    entry, reason = v.validate(_row(b"HELLO", [3, 5]))
    assert reason is None
    assert entry.buf == b"HELLO" and entry.sig == [3, 5]
    assert entry.cov_hash == coverage_hash([3, 5], b"HELLO")


@pytest.mark.parametrize("mutate,expect", [
    (lambda r: "not a dict", "schema:not-a-dict"),
    (lambda r: {**r, "content_b64": 7}, "schema:content_b64"),
    (lambda r: {**r, "content_b64": "!!not-b64!!"},
     "schema:content_b64-decode"),
    (lambda r: {**r, "content_b64": ""}, "schema:empty-content"),
    (lambda r: {**r, "md5": "zz" * 16}, "schema:md5"),
    (lambda r: {**r, "md5": "0" * 32}, "integrity:md5-mismatch"),
    (lambda r: {**r, "meta": "nope"}, "schema:meta"),
    (lambda r: {**r, "meta": {**r["meta"], "sig": ["x"]}},
     "schema:sig"),
    (lambda r: {**r, "meta": {**r["meta"], "edge_hits": {"a": "b"}}},
     "schema:edge_hits"),
    (lambda r: {**r, "meta": {**r["meta"], "selections": "lots"}},
     "schema:selections"),
    (lambda r: {**r, "cov_hash": "sig:forged"},
     "integrity:cov_hash-mismatch"),
])
def test_validator_rejects_poison(mutate, expect):
    entry, reason = EntryValidator().validate(mutate(_row(b"DATA",
                                                          [1])))
    assert entry is None and reason == expect


def test_validator_size_caps():
    v = EntryValidator(max_content_bytes=16, max_meta_bytes=64)
    assert v.validate(_row(b"X" * 17, [1]))[1] == "size:content"
    big_meta = _row(b"OK", [1])
    big_meta["meta"]["parent"] = "p" * 100
    assert v.validate(big_meta)[1] == "size:meta"


def test_validator_reexec_hook():
    """With a local executor the claimed signature must reproduce."""
    v = EntryValidator(executor=lambda buf: [1, 2])
    ok, reason = v.validate(_row(b"GOOD", [1, 2]))
    assert reason is None and ok is not None
    bad, reason = v.validate(_row(b"EVIL", [9]))
    assert bad is None and reason == "integrity:reexec-sig-mismatch"


def test_validator_never_raises_on_hostile_rows():
    """The validator IS the trust boundary: no input may crash it."""
    v = EntryValidator()
    hostile = [
        None, 42, [], {"content_b64": None},
        {"content_b64": "QQ==", "meta": {"sig": 3}},
        {"content_b64": "QQ==", "meta": {"seq": "NaNistan"}},
        {"content_b64": "QQ==", "cov_hash": {"not": "a string"}},
        {"content_b64": "QQ==", "meta": {"edge_hits": [1, 2]}},
    ]
    for row in hostile:
        entry, reason = v.validate(row)
        assert entry is None and isinstance(reason, str)


def test_quarantine_store_roundtrip(tmp_path):
    q = QuarantineStore(str(tmp_path))
    q.put(b"BAD", "integrity:cov_hash-mismatch", peer="evil")
    q.put(b"BAD", "integrity:cov_hash-mismatch", peer="evil")  # dedup
    assert len(q) == 1
    (md5, rec), = q.load()
    assert md5 == md5_hex(b"BAD")
    assert rec["reason"] == "integrity:cov_hash-mismatch"
    assert rec["peer"] == "evil"


# -- peer bans ----------------------------------------------------------


def test_peer_bans_threshold_and_decorrelated_backoff():
    clock = [1000.0]
    bans = PeerBans(threshold=3, base_s=10.0, cap_s=100.0,
                    rng=random.Random(7), time_fn=lambda: clock[0])
    assert not bans.strike("evil")          # 1
    assert not bans.strike("evil")          # 2
    assert bans.strike("evil")              # 3 -> banned
    assert bans.is_banned("evil") and bans.total_bans == 1
    first_len = bans.banned_until["evil"] - clock[0]
    assert 10.0 <= first_len <= 100.0
    # ban expires with the clock
    clock[0] += first_len + 1
    assert not bans.is_banned("evil")
    # next ban draws from U[base, 3x previous] — the decorrelated
    # jitter discipline (can exceed base when prev was long)
    assert bans.strike("evil", n=3)
    second_len = bans.banned_until["evil"] - clock[0]
    assert 10.0 <= second_len <= min(100.0, 3.0 * first_len)
    # clean pulls forgive strikes
    bans2 = PeerBans(threshold=3, rng=random.Random(1))
    bans2.strike("flaky", 2)
    bans2.clean("flaky")
    assert not bans2.strike("flaky")        # count restarted


# -- chaos: partition mode + match scoping ------------------------------


def test_chaos_partition_mode_is_endpoint_scoped():
    import urllib.error
    eng = chaos.configure({"faults": [
        {"point": "manager_rpc", "mode": "partition", "every": 1,
         "match": "127.0.0.1:9999"}]})
    # unmatched endpoint: untouched
    chaos.chaos_point("manager_rpc", url="http://127.0.0.1:1234/api")
    with pytest.raises(urllib.error.URLError, match="partition"):
        chaos.chaos_point("manager_rpc",
                          url="http://127.0.0.1:9999/api/corpus/c")
    # match-scoped faults count their OWN hits (deterministic given
    # the matched request sequence alone)
    assert eng.faults[0].seen == 1
    chaos.configure(None)


def test_chaos_match_scoped_hit_counting():
    eng = chaos.configure({"faults": [
        {"point": "manager_rpc", "mode": "http500", "hit": 2,
         "match": "peerX"}]})
    chaos.chaos_point("manager_rpc", url="http://peerX/a")  # seen 1
    for _ in range(5):      # unmatched traffic must not advance it
        chaos.chaos_point("manager_rpc", url="http://other/a")
    import urllib.error
    with pytest.raises(urllib.error.HTTPError):
        chaos.chaos_point("manager_rpc", url="http://peerX/b")
    assert eng.faults[0].fired == 1
    chaos.configure(None)


# -- gossip sidecar + hub-free exchange --------------------------------


def _sim(tmp_path, name, url="http://127.0.0.1:1", **kw):
    return SimWorker(name, "g1", url, str(tmp_path), **kw)


def test_sidecar_cursor_api_and_boot_nonce(tmp_path):
    w = _sim(tmp_path, "w1")
    try:
        w.discover(3)
        side = w.sync.sidecar
        with urllib.request.urlopen(
                f"{side.endpoint}/api/corpus/g1?since=0") as r:
            body = json.loads(r.read())
        assert body["latest"] == 3 and len(body["entries"]) == 3
        assert body["boot"] == side.boot
        # cursor paging: since=2 -> only the third row
        with urllib.request.urlopen(
                f"{side.endpoint}/api/corpus/g1?since=2") as r:
            page = json.loads(r.read())
        assert [e["id"] for e in page["entries"]] == [3]
        # publish dedups by cov_hash
        e = w.store.load()[0]
        assert not side.publish(e)
        with urllib.request.urlopen(
                f"{side.endpoint}/api/ping") as r:
            ping = json.loads(r.read())
        assert ping["entries"] == 3 and ping["worker"] == "w1"
    finally:
        w.close()


def test_peer_exchange_flows_without_any_manager(tmp_path):
    """THE demotion gate: two workers whose manager never existed
    still exchange their corpus peer-to-peer — the hub is a phone
    book, not the data path."""
    w1 = _sim(tmp_path, "w1")
    w2 = _sim(tmp_path, "w2")
    try:
        w1.discover(2)
        w2.discover(1)
        # no directory (manager dead): inject peers as a cached list
        w1.sync.peers = {"w2": w2.sync.sidecar.endpoint}
        w2.sync.peers = {"w1": w1.sync.sidecar.endpoint}
        for _ in range(2):
            w1.round()
            w2.round()
        union = w1.cov_hashes() | w2.cov_hashes()
        assert len(union) == 3
        assert w1.cov_hashes() == union == w2.cov_hashes()
        # rounds FAILED at the manager (backoff engaged) yet gossip
        # flowed: partitioned-from-hub is visible but not fatal
        assert w1.sync.consecutive_failures > 0
        assert w1.registry.counters.get("gossip_entries_in", 0) >= 1
        assert w1.registry.counters.get("gossip_rounds", 0) >= 2
    finally:
        w1.close()
        w2.close()


def test_peer_cursor_survives_truncated_pages(tmp_path, monkeypatch):
    """The sidecar caps each GET at PAGE rows; the pull cursor must
    advance by the page actually RETURNED, not jump to `latest` —
    jumping would permanently skip the rows the truncated page did
    not carry (fatal with the hub down, when peers are the only
    source)."""
    monkeypatch.setattr(GossipSidecar, "PAGE", 2)
    w1 = _sim(tmp_path, "w1")
    w2 = _sim(tmp_path, "w2")
    try:
        w1.discover(5)
        w2.sync.peers = {"w1": w1.sync.sidecar.endpoint}
        want = w1.cov_hashes()
        for i, expect in enumerate((2, 4, 5)):
            w2.round()
            assert len(w2.cov_hashes() & want) == expect, \
                f"round {i}: cursor lost truncated-page rows"
    finally:
        w1.close()
        w2.close()


def test_peer_cursor_resets_on_peer_restart(tmp_path):
    """A restarted sidecar restarts its row ids; the boot nonce must
    make pullers re-pull from 0 — and the reset must not be clobbered
    by the same response's `latest`."""
    w1 = _sim(tmp_path, "w1")
    w2 = _sim(tmp_path, "w2")
    try:
        w1.discover(3)
        w2.sync.peers = {"w1": w1.sync.sidecar.endpoint}
        w2.round()
        assert len(w2.cov_hashes()) == 3
        assert w2.sync._peer_cursor["w1"][1] == 3
        # simulate the peer restarting with a fresh (shorter) log
        side = w1.sync.sidecar
        with side._lock:
            side.boot = "restarted"
            kept = side._rows[:2]
            for i, row in enumerate(kept):
                row["id"] = i + 1
            side._rows = kept
        w2.round()      # sees the boot change: resets, admits nothing
        assert w2.sync._peer_cursor["w1"] == ["restarted", 0]
        w2.round()      # re-pulls from 0 (dedup absorbs the overlap)
        assert w2.sync._peer_cursor["w1"][1] == 2
    finally:
        w1.close()
        w2.close()


def test_sidecar_serves_from_store_without_heap_copy(tmp_path):
    """With a store attached, sidecar rows hold METADATA only (no
    second in-heap copy of the corpus); content is read from disk at
    serve time and the wire shape is unchanged."""
    w1 = _sim(tmp_path, "w1")
    w2 = _sim(tmp_path, "w2")
    try:
        w1.discover(3)
        side = w1.sync.sidecar
        with side._lock:
            assert all("_buf" not in r and "content_b64" not in r
                       for r in side._rows)
        w2.sync.peers = {"w1": side.endpoint}
        w2.round()
        assert len(w2.cov_hashes()) == 3    # lazy reads served fine
    finally:
        w1.close()
        w2.close()


def test_peer_cursor_ignores_malformed_row_id(tmp_path):
    """One hostile row with a garbage id must not collapse the
    page's ids to [] and trigger the latest-jump fallback (which
    would skip the truncated backlog)."""
    w1 = _sim(tmp_path, "w1")
    w2 = _sim(tmp_path, "w2")
    try:
        w1.discover(2)
        side = w1.sync.sidecar
        with side._lock:
            side._rows[0]["id"] = "x"       # hostile id
        w2.sync.peers = {"w1": side.endpoint}
        w2.round()
        # the good row's id (2) advanced the cursor; no jump past it
        assert w2.sync._peer_cursor["w1"][1] == 2
    finally:
        w1.close()
        w2.close()


def test_db_consume_recovery_is_one_shot():
    db = ManagerDB()
    assert not db.consume_recovery()        # never degraded
    db.degraded = True
    db._exec("SELECT 1")                    # a successful write path
    assert db.degraded is False
    assert db.consume_recovery() is True
    assert db.consume_recovery() is False   # one-shot
    db.close()


def test_journal_note_committed_never_truncates(tmp_path):
    """Truncation outside replay() could destroy a journal-only-ACKed
    record another handler is still mid-flight on — note_committed
    only accounts; replay() (lock-held) is the only truncation."""
    from killerbeez_tpu.manager.journal import AdmissionJournal
    j = AdmissionJournal(str(tmp_path / "j"), compact_bytes=1)
    j.append_corpus("c", "sig:x", "m", "w", b"DATA", None)
    j.note_committed()
    assert os.path.getsize(str(tmp_path / "j")) > 0   # kept
    assert j.needs_compact()
    db = ManagerDB()
    j.replay(db)                            # the safe compaction path
    assert os.path.getsize(str(tmp_path / "j")) == 0
    assert len(db.get_corpus_entries("c", 0)) == 1
    db.close()
    j.close()


def test_empty_directory_never_replaces_cached_peers(tmp_path):
    """A write-degraded manager freezes last_seen fleet-wide, so its
    directory can read empty while every peer is alive — the cached
    directory must survive, or gossip halts during exactly the
    outage it exists for."""
    s = ManagerServer(port=0)
    s.start()
    w = _sim(tmp_path, "w1", url=f"http://127.0.0.1:{s.port}")
    try:
        w.sync.peers = {"w9": "http://127.0.0.1:9"}
        w.sync._refresh_peers()     # directory empty server-side
        assert w.sync.peers == {"w9": "http://127.0.0.1:9"}
    finally:
        w.close()
        s.stop()


def test_peer_directory_ignores_liveness_while_degraded(tmp_path):
    """While DB writes fail, heartbeats can't refresh last_seen, so
    liveness classification is stale — the directory serves every
    registered endpoint instead of reading the fleet dead."""
    from killerbeez_tpu.manager.fleet import (
        FleetConfig, peer_directory,
    )
    db = ManagerDB()
    db.note_fleet_worker("c", "w1", meta={"gossip": "http://a:1"},
                         now=1.0)      # ancient: classifies DEAD
    cfg = FleetConfig()
    assert peer_directory(db, cfg, "c") == []
    db.degraded = True
    peers = peer_directory(db, cfg, "c")
    assert [p["worker"] for p in peers] == ["w1"]
    db.close()


def test_poisoned_peer_is_quarantined_and_banned(tmp_path):
    """Acceptance: a poisoned entry is never admitted, never crashes
    the worker, lands in the quarantine dir, and the offending peer
    is banned after the threshold."""
    evil = _sim(tmp_path, "evil")
    good = _sim(tmp_path, "good", ban_threshold=3)
    try:
        forged = evil.poison(4)
        evil.discover(1)            # honest entry rides along
        good.sync.peers = {"evil": evil.sync.sidecar.endpoint}
        good.round()
        # honest entry admitted, forged ones never
        got = good.cov_hashes()
        assert not (set(forged) & got)
        assert len(got - {e.cov_hash
                          for e in good.store.load()
                          if e.source == "local"}) <= 1
        reg = good.registry
        assert reg.counters.get("sync_quarantined", 0) == 4
        assert reg.counters.get("peers_banned", 0) == 1
        assert good.sync.bans.is_banned("evil")
        # quarantine artifacts on disk for the operator
        q = QuarantineStore(good.store.root)
        assert len(q) == 4
        assert all(rec["peer"] == "evil" for _, rec in q.load())
        # banned peer is excluded from subsequent fanout picks
        before = reg.counters.get("gossip_entries_in", 0)
        good.round()
        assert reg.counters.get("sync_quarantined", 0) == 4
        assert reg.counters.get("gossip_entries_in", 0) == before
    finally:
        evil.close()
        good.close()


def test_fuzzer_loop_runs_with_gossip_sync(tmp_path):
    """Loop integration: the production Fuzzer accepts a GossipSync
    wherever it took a CorpusSync — admissions publish through the
    sidecar, a second campaign pulls them (hub or peers), and the
    gossip counters land in the registry the stats sink reads."""
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory

    s = ManagerServer(port=0)
    s.start()
    url = f"http://127.0.0.1:{s.port}"

    def campaign(name, seed_n):
        instr = instrumentation_factory(
            "jit_harness",
            '{"target": "cgc_like", "novelty": "throughput"}')
        mut = mutator_factory("havoc", json.dumps({"seed": seed_n}),
                              b"CG\x02\x04\x05\x41xx")
        drv = driver_factory("file", None, instr, mut)
        sync = GossipSync(url, "loopg", worker=name,
                          interval_s=0.0)
        return Fuzzer(drv, output_dir=str(tmp_path / name),
                      batch_size=256, feedback=2,
                      corpus_dir=str(tmp_path / name / "c"),
                      sync=sync, persist_interval=0.0)

    try:
        f1 = campaign("g1", 11)
        f1.run(1024)
        assert f1.sync.pushed_n > 0
        f2 = campaign("g2", 22)
        f2.run(1024)
        assert f2.sync.pulled_n > 0
        assert "sync" in [a.source for a in f2.scheduler.arms]
        c = f2.telemetry.registry.counters
        assert c.get("gossip_rounds", 0) > 0
        # g2's sidecar serves everything it admitted or learned
        assert len(f2.sync.sidecar) >= f2.sync.pulled_n
    finally:
        f1.sync.close()
        f2.sync.close()
        s.stop()


# -- manager durability: WAL, locked retry, degraded mode, journal ------


def _post(url, path, payload):
    r = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, path):
    with urllib.request.urlopen(url + path, timeout=10) as resp:
        return json.loads(resp.read())


def _corpus_post(buf, sig, worker="w1"):
    return {"worker": worker, "md5": md5_hex(buf),
            "cov_hash": coverage_hash(sig, buf),
            "content_b64": base64.b64encode(buf).decode(),
            "meta": {"sig": sig, "md5": md5_hex(buf),
                     "cov_hash": coverage_hash(sig, buf)}}


def test_file_backed_db_runs_wal_with_busy_timeout(tmp_path):
    db = ManagerDB(str(tmp_path / "m.db"))
    conn = db._conn()
    assert conn.execute("PRAGMA journal_mode").fetchone()[0] == "wal"
    assert conn.execute("PRAGMA busy_timeout").fetchone()[0] \
        == ManagerDB.BUSY_TIMEOUT_MS
    db.close()


def test_db_write_retries_database_is_locked(tmp_path):
    """A lock burst (concurrent heartbeats) must retry with bounded
    backoff instead of 500ing the POST — PR 2's reject rule would
    otherwise drop that entry from sync forever."""
    import sqlite3
    db = ManagerDB(str(tmp_path / "m.db"))
    calls = {"n": 0}

    class FlakyConn:
        def execute(self, sql, params=()):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise sqlite3.OperationalError("database is locked")
            return db._conn().execute(sql, params)

        def rollback(self):
            pass

    cur = db._write(FlakyConn(), "SELECT 1")
    assert cur.fetchone()[0] == 1
    assert calls["n"] == 3 and not db.degraded
    # exhaustion: degraded latches and the typed error surfaces
    class AlwaysLocked:
        def execute(self, sql, params=()):
            raise sqlite3.OperationalError("database is locked")

        def rollback(self):
            pass

    with pytest.raises(ManagerWriteError):
        db._write(AlwaysLocked(), "SELECT 1")
    assert db.degraded
    db.close()


@pytest.fixture
def file_server(tmp_path):
    s = ManagerServer(port=0, db_path=str(tmp_path / "mgr.db"))
    s.start()
    yield s, f"http://127.0.0.1:{s.port}", str(tmp_path / "mgr.db")
    chaos.configure(None)
    s.stop()


def test_degraded_mode_keeps_serving_and_journal_acks(file_server):
    """ENOSPC on the corpus table: POSTs still ACK off the journal
    (201 + journaled flag — NOT the 4xx/5xx reject the worker would
    drop the entry over), cursor GETs keep serving, /api/health and
    /api/fleet read degraded, and recovery clears the latch."""
    s, url, _ = file_server
    assert _post(url, "/api/corpus/c1",
                 _corpus_post(b"ONE", [1]))[0] == 201
    chaos.configure({"faults": [
        {"point": "manager_db_write", "mode": "enospc", "every": 1,
         "match": "corpus_entries"}]})
    code, body = _post(url, "/api/corpus/c1",
                       _corpus_post(b"TWO", [2]))
    assert code == 201 and body["journaled"] and body["degraded"]
    # read-only: the cursor GET still serves what the DB has
    got = _get(url, "/api/corpus/c1?since=0")
    assert len(got["entries"]) == 1
    health = _get(url, "/api/health")
    assert health["degraded"] is True
    assert health["journal"]["uncommitted"] == 1
    assert _get(url, "/api/fleet")["degraded"] is True
    # events POST degrades identically
    chaos.configure({"faults": [
        {"point": "manager_db_write", "mode": "enospc", "every": 1,
         "match": "campaign_events"}]})
    code, body = _post(url, "/api/events/c1", {
        "worker": "w1",
        "events": [{"seq": 0, "t": 1.0, "type": "crash"}]})
    assert code == 201 and body["journaled"]
    # recovery: the next successful write clears the latch AND
    # replays the journal backlog in-process — the journal-only row
    # becomes visible to cursor GETs without any manager restart
    chaos.configure(None)
    assert _post(url, "/api/corpus/c1",
                 _corpus_post(b"THREE", [3]))[0] == 201
    assert _get(url, "/api/health")["degraded"] is False
    got = _get(url, "/api/corpus/c1?since=0")
    assert {e["md5"] for e in got["entries"]} == {
        md5_hex(b"ONE"), md5_hex(b"TWO"), md5_hex(b"THREE")}
    assert _get(url, "/api/health")["journal"]["uncommitted"] == 0


def test_journal_replays_acked_posts_after_manager_death(file_server,
                                                         tmp_path):
    """The SIGKILL-equivalence gate: rows ACKed journal-only while
    the DB was failing exist in the DB after a restart on the same
    paths — a killed manager loses ZERO accepted POSTs."""
    s, url, db_path = file_server
    _post(url, "/api/corpus/c2", _corpus_post(b"KEEP1", [1]))
    chaos.configure({"faults": [
        {"point": "manager_db_write", "mode": "enospc", "every": 1,
         "match": "corpus_entries"}]})
    _post(url, "/api/corpus/c2", _corpus_post(b"KEEP2", [2]))
    _post(url, "/api/events/c2", {
        "worker": "w1",
        "events": [{"seq": 0, "t": 1.0, "type": "crash",
                    "md5": "x"}]})
    chaos.configure(None)
    s.stop()        # the fixture's stop() later is a no-op double
    s2 = ManagerServer(port=0, db_path=db_path)
    try:
        rows = s2.db.get_corpus_entries("c2", 0)
        assert {r["md5"] for r in rows} \
            == {md5_hex(b"KEEP1"), md5_hex(b"KEEP2")}
        evs = s2.db.get_campaign_events("c2", 0)
        assert [e["event"]["seq"] for e in evs
                if e["worker"] == "w1"] == [0]
        # replay truncated the journal: a second boot replays nothing
        assert s2.journal.uncommitted == 0
        assert os.path.getsize(db_path + ".journal") == 0
    finally:
        s2.stop()


def test_peer_directory_registration_and_liveness(file_server):
    s, url, _ = file_server
    code, body = _post(url, "/api/peers/c3",
                       {"worker": "w1",
                        "endpoint": "http://127.0.0.1:7001"})
    assert code == 201 and body["peers"] == []   # self excluded
    _post(url, "/api/peers/c3", {"worker": "w2",
                                 "endpoint": "http://127.0.0.1:7002"})
    peers = _get(url, "/api/peers/c3")["peers"]
    assert {p["worker"]: p["endpoint"] for p in peers} == {
        "w1": "http://127.0.0.1:7001",
        "w2": "http://127.0.0.1:7002"}
    # a worker whose heartbeats stopped long ago drops out (DEAD)
    s.db.note_fleet_worker("c3", "w3", meta={"gossip": "http://x:1"},
                           now=1.0)
    names = {p["worker"] for p in _get(url, "/api/peers/c3")["peers"]}
    assert "w3" not in names and {"w1", "w2"} <= names
    # bad endpoints are refused
    with pytest.raises(urllib.error.HTTPError):
        _post(url, "/api/peers/c3", {"worker": "wX",
                                     "endpoint": "gopher://nope"})


def test_heartbeat_meta_merges_with_gossip_registration(file_server):
    """The gossip endpoint and the heartbeat's pid/host land in the
    same registry row without clobbering each other."""
    s, url, _ = file_server
    _post(url, "/api/peers/c4", {"worker": "w1",
                                 "endpoint": "http://127.0.0.1:7009"})
    _post(url, "/api/stats/c4", {
        "worker": "w1", "snapshot": {"counters": {"execs": 10}},
        "meta": {"pid": 123}})
    row, = s.db.get_fleet_workers("c4")
    assert row["meta"]["gossip"] == "http://127.0.0.1:7009"
    assert row["meta"]["pid"] == 123
    # directory still serves it after the heartbeat
    assert _get(url, "/api/peers/c4")["peers"][0]["endpoint"] \
        == "http://127.0.0.1:7009"


def test_fleet_view_surfaces_quarantine_and_ban_state(file_server):
    """kb-fleet --json reads workers.<w>.stats.sync_quarantined /
    peers_banned — the fleet-chaos CI lane asserts on these."""
    s, url, _ = file_server
    _post(url, "/api/stats/c5", {"worker": "w1", "snapshot": {
        "counters": {"execs": 100, "sync_quarantined": 7,
                     "peers_banned": 1, "gossip_entries_in": 42,
                     "gossip_entries_out": 17},
        "gauges": {"peers_banned_active": 1}}})
    body = _get(url, "/api/fleet/c5")
    stats = body["workers"]["w1"]["stats"]
    assert stats["sync_quarantined"] == 7
    assert stats["peers_banned"] == 1
    assert stats["peers_banned_active"] == 1
    assert stats["gossip_entries_in"] == 42
    assert stats["gossip_entries_out"] == 17
    # merged fleet counters fold them (aggregate.merge sums counters)
    assert body["merged"]["counters"]["sync_quarantined"] == 7
    # and /metrics exposes them through the parser-pinned surface
    with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
        text = r.read().decode()
    from tests.openmetrics_parser import parse_openmetrics
    families = parse_openmetrics(text)
    assert "kbz_sync_quarantined" in families
    assert "kbz_manager_degraded" in families
