"""Grammar/structure-aware generation tier (killerbeez_tpu/grammar/).

Pins the tier's contracts:
  * the PARITY ANCHOR — degenerate tables (``meta[0] == 0``) force
    every lane blind and ``grammar_havoc_at`` is bit-identical to
    ``havoc_at``; threading the degenerate grammar through the
    generation scans (single-chip -G and dp>1 mesh) leaves findings,
    virgin maps and corpus write-through bit-identical to the
    no-grammar path;
  * the structure compiler's edge cases: empty alphabets, empty
    rules, nesting beyond the depth cap (clipped to free bytes with
    ONE warning, never a miscompile), the entry-table bound, and
    deterministic recompiles;
  * the forward parse protects literals and length fields
    (``mut_mask``) while leaving token/blob bytes and everything
    past the structured prefix mutable;
  * auto-derivation (static dataflow -> grammar) compiles and runs
    over every built-in target family;
  * end to end: a structured campaign cracks a certified zoo deep
    edge at a budget where the A/B bench pins blind havoc to zero.
"""

import json
import os
import shutil

import jax
import numpy as np
import pytest

from killerbeez_tpu.drivers.factory import driver_factory
from killerbeez_tpu.fuzzer.loop import Fuzzer
from killerbeez_tpu.grammar.derive import derive_grammar
from killerbeez_tpu.grammar.device import grammar_havoc_at, parse_fields
from killerbeez_tpu.grammar.spec import (
    Grammar, Rule, blob, length, lit, load_grammar, ref, token,
)
from killerbeez_tpu.grammar.tables import (
    DEPTH_CAP, KIND_BLOB, MAX_ENTRIES, compile_grammar,
    degenerate_tables,
)
from killerbeez_tpu.instrumentation.factory import instrumentation_factory
from killerbeez_tpu.models.targets import get_target, target_names
from killerbeez_tpu.mutators.factory import mutator_factory
from killerbeez_tpu.ops.mutate_core import havoc_at

SEED = b"ABCD1234"


# ---------------------------------------------------------------------------
# the kernel parity anchor
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("stack_pow2", [2, 4])
def test_degenerate_kernel_bit_identical_to_havoc(stack_pow2):
    """grammar_havoc_at over degenerate tables == havoc_at, bit for
    bit, across lanes/lengths — the anchor the whole tier rests on."""
    gt = degenerate_tables().device()
    rng = np.random.default_rng(3)
    buf = jax.numpy.asarray(rng.integers(0, 256, 64).astype(np.uint8))
    for i in range(8):
        key = jax.random.PRNGKey(i)
        ln = jax.numpy.int32(4 + 7 * i)
        b0, l0 = havoc_at(buf, ln, key, stack_pow2=stack_pow2)
        b1, l1 = grammar_havoc_at(buf, ln, key, gt,
                                  stack_pow2=stack_pow2)
        assert np.array_equal(np.asarray(b0), np.asarray(b1))
        assert int(l0) == int(l1)


def test_nondegenerate_kernel_diverges_and_preserves_shape():
    g = Grammar(rules={"m": Rule("m", (
        lit(b"MAGI"), token([b"\x01", b"\x02"], 1),
        length(of="tail"), blob(0, name="tail")))}, start="m")
    gt = compile_grammar(g, stage_p=256).device()
    buf = jax.numpy.asarray(np.frombuffer(
        b"MAGI\x01\x03abc" + bytes(55), np.uint8))
    ln = jax.numpy.int32(9)
    diverged = False
    for i in range(8):
        key = jax.random.PRNGKey(i)
        b0, _ = havoc_at(buf, ln, key)
        b1, l1 = grammar_havoc_at(buf, ln, key, gt)
        assert b1.shape == buf.shape and 0 <= int(l1) <= 64
        diverged |= not np.array_equal(np.asarray(b0),
                                       np.asarray(b1))
    assert diverged, "structured stages never engaged"


# ---------------------------------------------------------------------------
# the forward parse: literal/length protection
# ---------------------------------------------------------------------------


def test_parse_fields_protects_lits_and_lens():
    g = Grammar(rules={"m": Rule("m", (
        lit(b"AB"), length(of="tail"),
        token([b"\x10\x20"], 2), blob(0, name="tail")))}, start="m")
    gt = compile_grammar(g).device()
    raw = b"AB\x04\x10\x20wxyz"
    buf = jax.numpy.asarray(np.frombuffer(raw + bytes(64 - len(raw)),
                                          np.uint8))
    pf = parse_fields(buf, jax.numpy.int32(len(raw)), gt)
    mask = np.asarray(pf.mut_mask)
    assert mask[0] == 0 and mask[1] == 0      # lit pinned
    assert mask[2] == 0                       # length field pinned
    assert mask[3] == 1 and mask[4] == 1      # token mutable
    assert mask[5:9].all()                    # blob mutable
    assert mask[len(raw):].all()              # past structure: anything


def test_parse_is_total_on_garbage():
    g = Grammar(rules={"m": Rule("m", (
        lit(b"AB"), length(of="t"), blob(0, name="t")))}, start="m")
    gt = compile_grammar(g).device()
    buf = jax.numpy.asarray(np.full(32, 0xFF, np.uint8))
    for ln in (0, 1, 31):
        pf = parse_fields(buf, jax.numpy.int32(ln), gt)
        assert np.asarray(pf.mut_mask).shape == (32,)
        out, _ = grammar_havoc_at(buf, jax.numpy.int32(ln),
                                  jax.random.PRNGKey(0),
                                  compile_grammar(g,
                                                  stage_p=256).device())
        assert out.shape == buf.shape


# ---------------------------------------------------------------------------
# the structure compiler: edge cases
# ---------------------------------------------------------------------------


def test_compile_empty_alphabet_guarded():
    g = Grammar(rules={"m": Rule("m", (token([], 1), blob(0)))},
                start="m")
    t = compile_grammar(g, stage_p=256)
    assert int(t.alpha_n[0]) == 0
    buf = jax.numpy.asarray(np.zeros(16, np.uint8))
    out, _ = grammar_havoc_at(buf, jax.numpy.int32(8),
                              jax.random.PRNGKey(1), t.device())
    assert out.shape == buf.shape       # kernels guard n == 0


def test_compile_empty_rule_is_degenerate():
    g = Grammar(rules={"m": Rule("m", ())}, start="m")
    t = compile_grammar(g)
    assert not t.nondegen               # "anything": the parity path


def test_compile_depth_cap_clips_with_one_warning(capsys):
    rules = {"m": Rule("m", (lit(b"X"), ref("m")))}
    t = compile_grammar(Grammar(rules=rules, start="m"))
    err = capsys.readouterr().err
    assert err.count("grammar: clipped") == 1   # one-shot warning
    assert int(t.meta[3]) > 0
    # the clip widened to free bytes, never narrowed
    assert KIND_BLOB in t.fp_kind.tolist()
    # lit depth: DEPTH_CAP expansions of "m" emit DEPTH_CAP lits
    assert t.fp_kind.tolist().count(0) == DEPTH_CAP


def test_compile_entry_cap_clips_with_warning(capsys):
    fields = tuple(lit(bytes([65 + (i % 26)]))
                   for i in range(MAX_ENTRIES + 8))
    t = compile_grammar(Grammar(
        rules={"m": Rule("m", fields)}, start="m"))
    assert int(t.meta[2]) == MAX_ENTRIES
    assert int(t.meta[3]) > 0
    assert capsys.readouterr().err.count("grammar: clipped") == 1


def test_compile_deterministic():
    g = derive_grammar(get_target("tlvstack_vm"))
    a, b = compile_grammar(g), compile_grammar(g)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)


def test_load_grammar_roundtrip_and_degenerate():
    g = Grammar(rules={"m": Rule("m", (
        lit(b"\x00\xFF"), token([b"ab"], 2), blob(0)))}, start="m")
    g2 = load_grammar(g.to_json())
    assert g2.to_json() == g.to_json()
    assert not compile_grammar(load_grammar("degenerate")).nondegen


# ---------------------------------------------------------------------------
# auto-derivation over every built-in target family
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(target_names()))
def test_derive_compile_run_every_builtin_target(name):
    """The static layer's facts always yield a compilable grammar
    whose kernel runs — over ALL built-in families."""
    prog = get_target(name)
    g = derive_grammar(prog)
    t = compile_grammar(g, stage_p=256)
    buf = jax.numpy.asarray(np.zeros(64, np.uint8))
    out, ln = grammar_havoc_at(buf, jax.numpy.int32(16),
                               jax.random.PRNGKey(0), t.device())
    assert out.shape == buf.shape and 0 <= int(ln) <= 64


# ---------------------------------------------------------------------------
# generation scans: degenerate parity, structured crack
# ---------------------------------------------------------------------------


def _findings(out_dir):
    res = {}
    for kind in ("new_paths", "crashes", "hangs"):
        d = os.path.join(out_dir, kind)
        res[kind] = sorted(os.listdir(d)) if os.path.isdir(d) else []
    return res


def test_generation_scan_degenerate_grammar_parity(tmp_path):
    """The single-chip -G scan with the degenerate grammar threaded
    is bit-identical to the no-grammar scan: findings, corpus
    write-through, virgin map."""
    def run(name, grammar):
        iopts = {"target": "test"}
        if grammar:
            iopts["grammar"] = "degenerate"
        instr = instrumentation_factory("jit_harness",
                                        json.dumps(iopts))
        mut = mutator_factory("havoc", '{"seed": 7}', SEED)
        drv = driver_factory("file", None, instr, mut)
        fz = Fuzzer(drv, output_dir=str(tmp_path / name),
                    batch_size=64, feedback=0, generations=4,
                    corpus_dir=str(tmp_path / name / "corpus"))
        fz.run(1024)
        return instr

    i0 = run("off", False)
    i1 = run("on", True)
    assert i1.grammar_tables is not None
    assert _findings(str(tmp_path / "on")) == \
        _findings(str(tmp_path / "off"))
    assert _findings(str(tmp_path / "on"))["new_paths"], "vacuous"
    assert np.array_equal(np.asarray(i0.virgin_bits),
                          np.asarray(i1.virgin_bits))

    def entries(name):
        d = tmp_path / name / "corpus"
        return sorted(f for f in os.listdir(d) if len(f) == 32)

    assert entries("on") == entries("off")


def test_mesh_generation_scan_degenerate_grammar_parity():
    """The dp>1 mesh scan with degenerate tables threaded is
    bit-identical to the no-grammar mesh scan, per shard."""
    from killerbeez_tpu.parallel import ShardedCampaignDriver
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")

    def run(grammar):
        iopts = {"target": "test"}
        if grammar:
            iopts["grammar"] = "degenerate"
        instr = instrumentation_factory("jit_harness",
                                        json.dumps(iopts))
        mut = mutator_factory("havoc", '{"seed": 7}', SEED)
        drv = ShardedCampaignDriver("2,1", instr, mut,
                                    batch_size=128)
        out = drv.test_batch_generations(128, 4)
        return out.materialize(), instr

    h0, i0 = run(False)
    h1, i1 = run(True)
    found = 0
    for d in range(2):
        s0, s1 = h0.shard(d), h1.shard(d)
        assert int(s0.fr_ptr) == int(s1.fr_ptr)
        st = min(int(s0.fr_ptr), int(s0.cap))
        found += st
        assert np.array_equal(s0.fr_bufs[:st], s1.fr_bufs[:st])
        assert np.array_equal(s0.adm_bufs, s1.adm_bufs)
    assert found > 0, "vacuous"
    assert np.array_equal(np.asarray(i0.virgin_bits),
                          np.asarray(i1.virgin_bits))


def test_mesh_generation_scan_structured_grammar_runs():
    """A NON-degenerate grammar threads through the dp>1 mesh scan
    (trailing replicated pytree spec) and produces findings."""
    from killerbeez_tpu.models.zoo import build_zoo
    from killerbeez_tpu.parallel import ShardedCampaignDriver
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")
    t = build_zoo("zoo:tlv:depth=2,bug=1")
    instr = instrumentation_factory("jit_harness", json.dumps(
        {"target": t.name, "grammar": t.grammar.to_json()}))
    mut = mutator_factory("havoc", '{"seed": 7}', t.seed)
    drv = ShardedCampaignDriver("2,1", instr, mut, batch_size=128)
    out = drv.test_batch_generations(128, 4).materialize()
    assert sum(int(out.shard(d).fr_ptr) for d in range(2)) > 0


def test_structured_campaign_cracks_certified_zoo_deep_edge(tmp_path):
    """End to end at a deliberately small budget: the structured -G
    campaign reaches a zoo family's certified deep edge (the A/B
    bench additionally pins blind havoc to ZERO at 8x this budget —
    bench.py --grammar --gate)."""
    from killerbeez_tpu.models.zoo import build_zoo
    t = build_zoo("zoo:tlv:depth=2,bug=1")
    instr = instrumentation_factory("jit_harness", json.dumps(
        {"target": t.name, "novelty": "throughput",
         "grammar": t.grammar.to_json()}))
    mut = mutator_factory("havoc", '{"seed": 7}', t.seed)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "crack"),
                batch_size=256, write_findings=False,
                generations=4, feedback=0)
    fz.run(2048)
    ef = np.asarray(t.program.edge_from)
    et = np.asarray(t.program.edge_to)
    slots = np.asarray(t.program.edge_slot)
    vb = np.asarray(instr.virgin_bits)
    hit = any(int(vb[slots[e]]) != 0xFF for e in range(len(et))
              if (int(ef[e]), int(et[e])) == t.deep_edge)
    assert hit and fz.stats.crashes > 0


# ---------------------------------------------------------------------------
# option plumbing / stand-down rules
# ---------------------------------------------------------------------------


def test_grammar_needs_xla_engine():
    from killerbeez_tpu.parallel.distributed import _ShardKernels
    k = _ShardKernels.__new__(_ShardKernels)
    k.engine = "pallas_fused"
    with pytest.raises(ValueError, match="xla engine"):
        k.mutate_exec(None, None, None,
                      grammar_tables=degenerate_tables().device())


def test_grammar_and_learn_exclusive():
    with pytest.raises(ValueError, match="mutually exclusive"):
        instrumentation_factory("jit_harness", json.dumps(
            {"target": "test", "grammar": "degenerate", "learn": 1}))


# ---------------------------------------------------------------------------
# VSA-sourced facts (derive_grammar(vsa=); analysis/vsa.py consumer)
# ---------------------------------------------------------------------------


def test_derive_vsa_only_facts_nondegenerate():
    """A program whose ONLY facts are VSA-derived (an affine guard
    against an out-of-byte-range constant — invisible to the literal
    guarding-constant pass) must still derive a non-degenerate
    grammar once the value-set tier feeds it."""
    from killerbeez_tpu.analysis.vsa import analyze_vsa
    from killerbeez_tpu.models.compiler import Assembler
    a = Assembler("affine_only", mem_size=16, max_steps=64)
    a.block()
    a.ldi(1, 0)
    a.ldb(0, 1)
    a.addi(0, 0, 200)                   # only fact: b0+200 == 300
    a.ldi(2, 300)
    a.br("eq", 0, 2, "win")
    a.block()
    a.halt()
    a.label("win")
    a.block()
    a.crash()
    prog = a.build()
    # the literal pass alone: degenerate (one free blob, no pins)
    g0 = derive_grammar(prog)
    assert not compile_grammar(g0).nondegen
    # with VSA: byte 0 pinned to the inverted guard value
    g1 = derive_grammar(prog, vsa=analyze_vsa(prog))
    fields = g1.rules["msg"].fields
    assert fields[0].kind == "lit" and fields[0].value == bytes([100])
    assert compile_grammar(g1).nondegen


def test_derive_degenerate_parity_survives_vsa_source():
    """The degenerate-grammar bit-parity guarantee (derive.py
    doctrine) must survive the new fact source: a program VSA can
    say nothing useful about still derives the degenerate grammar,
    and it still compiles to the blind-parity tables."""
    from killerbeez_tpu.analysis.vsa import analyze_vsa
    from killerbeez_tpu.models.compiler import Assembler
    a = Assembler("no_facts", mem_size=16, max_steps=64)
    a.block()
    a.ldi(1, 0)
    a.ldb(0, 1)                         # read a byte, gate nothing
    a.halt()
    prog = a.build()
    g0 = derive_grammar(prog)
    g1 = derive_grammar(prog, vsa=analyze_vsa(prog))
    assert g0 == g1                     # the fact source added nothing
    t = compile_grammar(g1)
    assert not t.nondegen               # still the blind-parity tables
    # and on a REAL target the vsa=None path is the exact pre-VSA
    # derivation (the parity anchor for existing campaigns)
    real = get_target("tlvstack_vm")
    assert derive_grammar(real) == derive_grammar(real, vsa=None)
