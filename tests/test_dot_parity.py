"""Fast-lane bf16 dot-mode parity (ops/vm_kernel.py dot_modes).

The exact-bf16 one-hot MXU dots ("bf16x2"/"bf16") must be
bit-identical to the f32 HIGHEST path.  The heavyweight
engine-equivalence sweeps live in test_vm_kernel.py (nightly lane);
this file keeps ONE interpret-mode parity check in the per-push lane
so a dot-mode regression can't slip through between nightlies.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from killerbeez_tpu.models import targets
from killerbeez_tpu.ops.vm_kernel import (
    LANE_TILE, dot_modes, run_batch_pallas,
)


@pytest.mark.parametrize("name", ["test", "tlvstack_vm"])
def test_fast_dots_match_f32(name, rng):
    prog = targets.get_target(name)
    fast = dot_modes(prog.instrs, prog.n_edges)
    assert fast != ("f32", "f32"), (
        f"{name} no longer qualifies for the fast dot modes; pick a "
        "fixture that does so the bf16 path stays covered per-push")
    B, L = LANE_TILE, 24
    inputs = rng.integers(0, 256, (B, L)).astype(np.uint8)
    lengths = rng.integers(1, L + 1, B).astype(np.int32)
    args = (jnp.asarray(prog.instrs), jnp.asarray(prog.edge_table),
            jnp.asarray(inputs), jnp.asarray(lengths),
            prog.mem_size, prog.max_steps, prog.n_edges)
    ref = run_batch_pallas(*args, interpret=True, dots=("f32", "f32"))
    out = run_batch_pallas(*args, interpret=True, dots=fast)
    for f in ("status", "exit_code", "counts", "steps", "path_hash"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f)), np.asarray(getattr(out, f)),
            err_msg=f"{name} dots={fast}: {f} diverged from f32")
