"""End-to-end acceptance tests — the smoke-test contract (SURVEY §4):
the fuzzer finds the ABCD crash from seed "ABC@", new-path counts are
exact in `exact` novelty mode, findings land in output dirs, state
round-trips, and host-exec backends classify crash/hang/none."""

import json
import os
import stat
import sys

import numpy as np
import pytest

from killerbeez_tpu import FUZZ_CRASH, FUZZ_HANG, FUZZ_NONE
from killerbeez_tpu.drivers.factory import driver_factory, driver_help
from killerbeez_tpu.fuzzer.cli import main as cli_main
from killerbeez_tpu.fuzzer.loop import Fuzzer
from killerbeez_tpu.instrumentation.factory import (
    instrumentation_factory, instrumentation_help,
)
from killerbeez_tpu.mutators.factory import mutator_factory

SEED = b"ABC@"


def make_fuzzer(tmp_path, mutator="bit_flip", mopts=None,
                iopts='{"target": "test"}', batch=64):
    instr = instrumentation_factory("jit_harness", iopts)
    mut = mutator_factory(mutator, mopts, SEED)
    drv = driver_factory("file", None, instr, mut)
    return Fuzzer(drv, output_dir=str(tmp_path / "output"),
                  batch_size=batch), instr, mut


def test_bit_flip_finds_abcd_crash(tmp_path):
    fz, instr, _ = make_fuzzer(tmp_path)
    stats = fz.run(32)  # full bit_flip walk of a 4-byte seed
    assert stats.iterations == 32
    assert stats.crashes == 1
    assert stats.unique_crashes == 1
    crash_dir = tmp_path / "output" / "crashes"
    files = os.listdir(crash_dir)
    assert len(files) == 1
    assert (crash_dir / files[0]).read_bytes() == b"ABCD"


def test_exact_new_path_counts(tmp_path):
    """Parity gate: from seed ABC@, the bit_flip walk reaches exactly
    one brand-new block (the crash path); every candidate that stays
    on the ABC-prefix path is not new after the first exec."""
    fz, instr, _ = make_fuzzer(tmp_path, batch=8)  # batches of 8, exact
    stats = fz.run(32)
    # candidate 0 (flip bit 0 -> "\xc1BC@") leaves the A-path: new.
    # Further flips in byte 0 change in[0] too -> same "exit early"
    # path, not new. The exact-mode count must be stable run-to-run:
    fz2, _, _ = make_fuzzer(tmp_path.joinpath("b"), batch=32)
    stats2 = fz2.run(32)
    assert stats.new_paths == stats2.new_paths  # batch-size invariant
    assert stats.crashes == stats2.crashes == 1


def test_throughput_mode_finds_same_crash(tmp_path):
    fz, _, _ = make_fuzzer(
        tmp_path, iopts='{"target": "test", "novelty": "throughput"}',
        batch=32)
    stats = fz.run(32)
    assert stats.crashes == 1


def test_havoc_on_cgc_like_finds_planted_bug(tmp_path):
    """The cgc_like type-2 OOB store should fall to havoc from a
    valid-format seed within a few thousand execs."""
    seed = b"CG\x02\x04\x05\x41xx"
    instr = instrumentation_factory("jit_harness",
                                    '{"target": "cgc_like"}')
    mut = mutator_factory("havoc", '{"seed": 11}', seed)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=512)
    stats = fz.run(4096)
    assert stats.crashes > 0
    assert stats.new_paths > 0


def test_hang_detection_batched(tmp_path):
    instr = instrumentation_factory("jit_harness", '{"target": "hang"}')
    mut = mutator_factory("havoc", '{"seed": 3}', b"Hello")
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=128)
    stats = fz.run(256)
    assert stats.hangs > 0
    assert stats.unique_hangs >= 1
    assert os.listdir(tmp_path / "o" / "hangs")


def test_instrumentation_state_roundtrip_and_merge(tmp_path):
    fz, instr, _ = make_fuzzer(tmp_path)
    fz.run(32)  # full walk: byte-3 flips cover the seed's own path
    state = instr.get_state()
    # a fresh instance loaded from state sees nothing new on replay
    instr2 = instrumentation_factory("jit_harness", '{"target": "test"}')
    instr2.set_state(state)
    instr2.enable(SEED)
    assert instr2.is_new_path() == 0
    # merge: fold coverage of two halves == full-run coverage
    ia = instrumentation_factory("jit_harness", '{"target": "test"}')
    ib = instrumentation_factory("jit_harness", '{"target": "test"}')
    ia.enable(b"AXXX")
    ib.enable(b"ABXX")
    ia.merge(ib.get_state())
    ic = instrumentation_factory("jit_harness", '{"target": "test"}')
    ic.set_state(ia.get_state())
    ic.enable(b"AXXX")
    assert ic.is_new_path() == 0
    ic.enable(b"ABXX")
    assert ic.is_new_path() == 0
    ic.enable(b"ABCX")  # not covered by either half
    assert ic.is_new_path() == 2


def test_state_rejects_wrong_component():
    instr = instrumentation_factory("jit_harness", '{"target": "test"}')
    with pytest.raises(ValueError):
        instr.set_state(json.dumps({"instrumentation": "afl"}))


def test_jit_harness_requires_target():
    with pytest.raises(ValueError, match="target"):
        instrumentation_factory("jit_harness", None)


def test_mutator_exhaustion_stops_loop(tmp_path):
    fz, _, mut = make_fuzzer(tmp_path)
    stats = fz.run(-1)  # run to exhaustion
    assert stats.iterations == 32
    assert mut.remaining() == 0


def test_cli_end_to_end(tmp_path, capsys):
    seed_path = tmp_path / "seed"
    seed_path.write_bytes(SEED)
    out = tmp_path / "out"
    rc = cli_main([
        "file", "jit_harness", "bit_flip",
        "-i", '{"target": "test"}',
        "-sf", str(seed_path), "-n", "32", "-o", str(out),
        "-isd", str(tmp_path / "istate.json"),
        "-msd", str(tmp_path / "mstate.json"),
        "-b", "16",
    ])
    assert rc == 0
    assert len(os.listdir(out / "crashes")) == 1
    istate = json.loads((tmp_path / "istate.json").read_text())
    assert istate["total_execs"] == 32
    mstate = json.loads((tmp_path / "mstate.json").read_text())
    assert mstate["iteration"] == 32


def test_cli_resume_from_state(tmp_path):
    seed_path = tmp_path / "seed"
    seed_path.write_bytes(SEED)
    out = tmp_path / "out"
    common = ["file", "jit_harness", "bit_flip", "-i",
              '{"target": "test"}', "-sf", str(seed_path), "-o", str(out)]
    rc = cli_main(common + ["-n", "16", "-msd", str(tmp_path / "m.json"),
                            "-isd", str(tmp_path / "i.json")])
    assert rc == 0
    assert not os.listdir(out / "crashes")  # crash is at iteration 29
    rc = cli_main(common + ["-n", "16", "-msf", str(tmp_path / "m.json"),
                            "-isf", str(tmp_path / "i.json")])
    assert rc == 0
    assert len(os.listdir(out / "crashes")) == 1  # found after resume


def test_cli_errors(tmp_path, capsys):
    assert cli_main(["file", "jit_harness", "nope", "-ss", "x",
                     "-i", '{"target": "test"}']) == 2
    assert "unknown mutator" in capsys.readouterr().err
    assert cli_main(["file", "jit_harness", "bit_flip"]) == 2  # no seed
    rc = cli_main(["--list", "file", "jit_harness", "bit_flip"])
    assert rc == 0
    assert "jit_harness" in capsys.readouterr().out


def test_help_aggregation():
    assert "file driver" in driver_help()
    assert "jit_harness" in instrumentation_help()


def test_single_exec_path_tracks_unique_crashes(tmp_path):
    """The scalar loop must propagate unique-crash flags (the batch
    path isn't the only consumer of AFL-map uniqueness)."""
    instr = instrumentation_factory("jit_harness", '{"target": "test"}')
    mut = mutator_factory("bit_flip", None, SEED)
    drv = driver_factory("file", None, instr, mut)
    drv_supports = drv.supports_batch
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=8)
    fz._run_single(32)  # force the scalar loop regardless of support
    assert drv_supports  # sanity: batch path exists but wasn't used
    assert fz.stats.crashes == 1
    assert fz.stats.unique_crashes == 1


def test_write_findings_false_still_dedups(tmp_path):
    instr = instrumentation_factory("jit_harness", '{"target": "test"}')
    mut = mutator_factory("nop", None, b"ABCD")  # crashes every iter
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=4,
                write_findings=False)
    stats = fz.run(16)
    assert stats.crashes == 16
    # identical input -> recorded (logged) once, no files written —
    # including stats files: a no-artifacts run stays artifact-free
    # (the registry still counts; only the sink is disabled)
    assert not os.path.exists(tmp_path / "o")
    assert len(fz._seen["crashes"]) == 1
    assert fz.telemetry.sink is None
    assert fz.stats.execs_per_sec > 0


def test_tail_batch_padding_keeps_counts(tmp_path):
    """n_iterations not divisible by batch_size: padding lanes must
    not inflate stats."""
    fz, instr, _ = make_fuzzer(tmp_path, batch=24)  # rooms: 24, 8
    stats = fz.run(32)
    assert stats.iterations == 32
    assert stats.crashes == 1
    assert instr.total_execs == 48  # 2 padded device batches of 24


# -- host-exec backend (return_code) ----------------------------------

def _script(tmp_path, name, body):
    p = tmp_path / name
    p.write_text("#!/bin/sh\n" + body + "\n")
    p.chmod(p.stat().st_mode | stat.S_IXUSR)
    return str(p)


def test_return_code_file_driver(tmp_path):
    target = _script(tmp_path, "crasher.sh",
                     'grep -q ABCD "$1" && kill -SEGV $$ ; exit 0')
    instr = instrumentation_factory("return_code", '{"timeout": 5}')
    mut = mutator_factory("bit_flip", None, SEED)
    drv = driver_factory(
        "file", json.dumps({"path": target, "arguments": "@@"}),
        instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=1)
    stats = fz.run(32)
    assert stats.iterations == 32
    assert stats.crashes == 1
    assert stats.new_paths == 0  # dumb fuzzing has no coverage


def test_return_code_stdin_driver_and_hang(tmp_path):
    target = _script(tmp_path, "stdin_t.sh",
                     'read line; [ "$line" = "HANG" ] && sleep 30; exit 0')
    instr = instrumentation_factory("return_code", '{"timeout": 0.5}')
    drv = driver_factory("stdin", json.dumps({"path": target}), instr)
    assert drv.test_input(b"ok\n") == FUZZ_NONE
    assert drv.test_input(b"HANG\n") == FUZZ_HANG


def test_return_code_missing_binary(tmp_path):
    instr = instrumentation_factory("return_code", None)
    drv = driver_factory("file",
                         '{"path": "/nonexistent/binary"}', instr,
                         mutator_factory("nop", None, SEED))
    from killerbeez_tpu import FUZZ_ERROR
    assert drv.test_input(b"x") == FUZZ_ERROR


def test_exact_gate_switches_default_at_large_batch():
    """The exact (sequential) scan is for parity gates; a large batch
    under the DEFAULT novelty must warn and switch to throughput,
    while an EXPLICIT exact request is honored (with a warning)."""
    import io
    from killerbeez_tpu.utils import logging as kblog
    big = np.zeros((2048, 8), dtype=np.uint8)
    lens = np.full(2048, 4, dtype=np.int32)
    buf = io.StringIO()
    old_stream = kblog._state.stream
    kblog._state.stream = buf
    try:
        instr = instrumentation_factory("jit_harness",
                                        '{"target": "test"}')
        assert instr.exact
        instr.run_batch(big, lens)
        assert not instr.exact                  # default switched
        assert "throughput" in buf.getvalue()

        buf.truncate(0)
        forced = instrumentation_factory(
            "jit_harness", '{"target": "test", "novelty": "exact"}')
        forced.run_batch(big, lens)
        assert forced.exact                     # explicit wins
        assert "slow" in buf.getvalue()
    finally:
        kblog._state.stream = old_stream


def test_debug_triage_post_pass(tmp_path, corpus_bin):
    """VERDICT weak #6: unique crashes re-run once under the ptrace
    debug tier — fuzzing stays batched, crash detail (signal, fault
    address, module-relative PC) lands next to the repro."""
    instr = instrumentation_factory("afl", None)
    mut = mutator_factory("bit_flip", None, b"ABC@")
    drv = driver_factory("stdin", json.dumps(
        {"path": corpus_bin("test")}), instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=32,
                debug_triage=True)
    stats = fz.run(32)
    assert stats.unique_crashes == 1
    crash_dir = tmp_path / "o" / "crashes"
    infos = [p for p in os.listdir(crash_dir) if p.endswith(".info")]
    assert len(infos) == 1
    text = (crash_dir / infos[0]).read_text()
    assert "SIGSEGV" in text and "pc=0x" in text
    drv.cleanup()
    instr.cleanup()


def test_afl_padding_sentinel(corpus_bin):
    """VERDICT weak #8: result-array padding carries a loud sentinel
    (FUZZ_ERROR) rather than plausible exit-0 statuses."""
    instr = instrumentation_factory("afl", None)
    instr.prepare_host(corpus_bin("test"), use_stdin=True)
    inputs = np.zeros((3, 4), dtype=np.uint8)
    inputs[0, :4] = np.frombuffer(b"ABCD", dtype=np.uint8)
    res = instr.run_batch(inputs, np.full(3, 4, dtype=np.int32),
                          pad_to=8)
    assert res.statuses[0] == FUZZ_CRASH
    assert (res.statuses[3:] == 4).all()       # FUZZ_ERROR sentinel
    assert (res.new_paths[3:] == 0).all()
    assert instr.total_execs == 3              # padding cost nothing
    instr.cleanup()


def test_pipeline_drains_findings_on_error(tmp_path):
    """The loop keeps batches in flight; findings from already-
    executed batches must survive a mid-run failure (the drain runs
    in a finally block)."""
    fz, instr, _ = make_fuzzer(tmp_path, mutator="havoc",
                               mopts='{"seed": 1}', batch=8)
    orig = fz.driver.test_batch
    calls = {"n": 0}

    def flaky(room, pad_to=None, prefetch_next=True):
        calls["n"] += 1
        if calls["n"] > 3:
            raise RuntimeError("injected failure")
        return orig(room, pad_to=pad_to, prefetch_next=prefetch_next)

    fz.driver.test_batch = flaky
    with pytest.raises(RuntimeError, match="injected"):
        fz.run(1024)
    # batches 1-3 executed before the failure: their findings (the
    # ABCD crash falls out of havoc on an 8-byte seed quickly, and
    # new paths always appear in batch 1) must be on disk
    assert fz.stats.new_paths > 0
    assert os.listdir(tmp_path / "output" / "new_paths")


def _interpret_pallas(monkeypatch):
    """Route the pallas entries through interpret mode (CI has no
    TPU) and clear the jit caches that captured them."""
    import killerbeez_tpu.instrumentation.jit_harness as jh
    import killerbeez_tpu.ops.vm_kernel as vk
    orig_fuzz = vk.fuzz_batch_pallas
    orig_run = vk.run_batch_pallas
    monkeypatch.setattr(
        vk, "fuzz_batch_pallas",
        lambda *a, **k: orig_fuzz(*a, **{**k, "interpret": True}))
    monkeypatch.setattr(
        vk, "run_batch_pallas",
        lambda *a, **k: orig_run(*a, **{**k, "interpret": True}))
    jh._fused_step.clear_cache()
    jh._fused_fuzz_step.clear_cache()
    return (jh._fused_step, jh._fused_fuzz_step)


@pytest.mark.slow  # ~40s interpret-mode sweep: nightly lane
def test_fused_cli_path_matches_unfused(tmp_path, monkeypatch):
    """The product path for the flagship number: engine
    "pallas_fused" + havoc drives mutation AND execution in one
    kernel from the ordinary Fuzzer loop, and must produce IDENTICAL
    stats and on-disk findings to the unfused engine (same mutator
    keys -> bit-identical candidates and verdicts)."""
    from killerbeez_tpu.models import targets_cgc
    steps = _interpret_pallas(monkeypatch)
    seed = targets_cgc.tlvstack_vm_seed()
    try:
        runs = {}
        for engine in ("xla", "pallas_fused"):
            instr = instrumentation_factory(
                "jit_harness",
                json.dumps({"target": "tlvstack_vm", "engine": engine}))
            mut = mutator_factory("havoc", '{"seed": 5}', seed)
            drv = driver_factory("file", None, instr, mut)
            out = tmp_path / engine
            fz = Fuzzer(drv, output_dir=str(out), batch_size=128)
            stats = fz.run(256)
            findings = {
                kind: sorted(os.listdir(out / kind))
                for kind in ("crashes", "hangs", "new_paths")}
            runs[engine] = (stats.as_dict(), findings,
                            instr.get_state(), mut.iteration)
    finally:
        for s in steps:
            s.clear_cache()
    (s_x, f_x, st_x, it_x), (s_f, f_f, st_f, it_f) = (
        runs["xla"], runs["pallas_fused"])
    assert f_x == f_f                       # identical findings on disk
    assert f_x["new_paths"]                 # non-vacuous
    assert s_x["new_paths"] == s_f["new_paths"]
    assert s_x["crashes"] == s_f["crashes"]
    assert it_x == it_f == 256              # mutator walk advanced
    # virgin maps identical too (state interchangeable across engines)
    a, b = json.loads(st_x), json.loads(st_f)
    assert a["virgin_bits"] == b["virgin_bits"]
    assert a["virgin_crash"] == b["virgin_crash"]


def test_fused_engine_falls_back_for_unfusable_mutator(tmp_path,
                                                      monkeypatch):
    """engine "pallas_fused" with a non-havoc mutator warns and runs
    the unfused pallas engine — never silently wrong results."""
    steps = _interpret_pallas(monkeypatch)
    try:
        instr = instrumentation_factory(
            "jit_harness",
            '{"target": "test", "engine": "pallas_fused"}')
        mut = mutator_factory("bit_flip", None, SEED)
        drv = driver_factory("file", None, instr, mut)
        assert not instr.wants_fused(mut)   # warns once, returns False
        fz = Fuzzer(drv, output_dir=str(tmp_path / "out"), batch_size=8)
        stats = fz.run(32)
        assert stats.crashes == 1           # the ABCD crash still found
    finally:
        for s in steps:
            s.clear_cache()


def test_gate_flip_overreports_never_underreports(tmp_path):
    """docs/USAGE.md "known counting semantics" pinned: throughput
    novelty (the default above EXACT_BATCH_GATE lanes) may count
    MORE new-path lanes than the sequential exact scan on the same
    candidates — never fewer — and every finding the exact scan
    writes is also on disk in throughput mode (a superset: an
    already-covered sub-path can look new vs the incoming map)."""
    from killerbeez_tpu.models import targets_cgc
    seed = targets_cgc.tlvstack_vm_seed()
    stats = {}
    files = {}
    for mode in ("exact", "throughput"):
        instr = instrumentation_factory(
            "jit_harness",
            json.dumps({"target": "tlvstack_vm", "novelty": mode}))
        mut = mutator_factory("havoc", '{"seed": 9}', seed)
        drv = driver_factory("file", None, instr, mut)
        out = tmp_path / mode
        fz = Fuzzer(drv, output_dir=str(out), batch_size=256)
        stats[mode] = fz.run(512).new_paths
        files[mode] = sorted(os.listdir(out / "new_paths"))
    assert stats["throughput"] >= stats["exact"]
    assert stats["exact"] > 0
    assert set(files["exact"]) <= set(files["throughput"])


def test_corpus_feedback_rotation_mechanism(tmp_path):
    """Corpus feedback (-fb): new-path findings re-enter the run as
    mutation seeds via the decayed-bandit arm selection
    (docs/USAGE.md).  Pins the MECHANISM: rotation actually happens
    with zero recompiles (shape-stable seed swaps), only edge-novel
    findings are admitted (as [buf, selections, finds] arms whose
    stats the bandit maintains), the walk position stays monotonic
    (no candidate replay), and the guided run keeps finding paths.
    The coverage-at-budget WIN over single-seed havoc is measured
    separately on real hardware (profiling/fb_gate.py; 2 of 3 CGC
    targets)."""
    from killerbeez_tpu.models import targets_cgc
    seed = targets_cgc.tlvstack_vm_seed()
    instr = instrumentation_factory(
        "jit_harness",
        '{"target": "tlvstack_vm", "novelty": "throughput"}')
    mut = mutator_factory("havoc", '{"seed": 2}', seed)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "fb"),
                batch_size=256, write_findings=False, feedback=2)
    stats = fz.run(4096)
    assert stats.new_paths > 0
    assert fz._corpus, "no findings admitted to the rotation corpus"
    assert fz._rotations > 0, "rotation never happened"
    # bandit bookkeeping: arms are [buf, selections, finds], periods
    # were credited somewhere (decay keeps values fractional), and
    # the stats can never go negative
    assert all(len(a) == 3 for a in fz._corpus)
    assert fz._base_stats[0] > 0, "no period ever credited to base"
    assert all(a[1] >= 0 and a[2] >= 0 for a in fz._corpus)
    # the base seed anchors the cycle and swaps kept the tensor width
    assert fz._base_seed == seed
    assert mut.max_length == len(fz.driver.mutator.seed_buf)
    # monotonic walk: iteration equals the global exec count even
    # across rotations (no (seed, iteration) pair replayed)
    assert mut.get_current_iteration() == 4096
    # an unguided control run on the same stream stays in the same
    # coverage band (rotation is a trade, not a cliff)
    instr2 = instrumentation_factory(
        "jit_harness",
        '{"target": "tlvstack_vm", "novelty": "throughput"}')
    mut2 = mutator_factory("havoc", '{"seed": 2}', seed)
    drv2 = driver_factory("file", None, instr2, mut2)
    fz2 = Fuzzer(drv2, output_dir=str(tmp_path / "nofb"),
                 batch_size=256, write_findings=False)
    fz2.run(4096)
    assert instr.coverage_bytes() >= 0.75 * instr2.coverage_bytes()


def test_stats_files_written_and_consistent(tmp_path):
    """Acceptance gate for the telemetry subsystem: a short campaign
    writes AFL-compatible fuzzer_stats + plot_data + stats.jsonl, and
    the streams AGREE — the sum of plot_data row deltas equals the
    fuzzer_stats cumulative counters, and the registry's lifetime
    rate is consistent with execs/elapsed.  Runs the CGC-grade
    flagship target on the CPU backend with a small batch — the
    telemetry acceptance configuration."""
    from killerbeez_tpu.models import targets_cgc
    from killerbeez_tpu.telemetry import parse_fuzzer_stats
    instr = instrumentation_factory(
        "jit_harness",
        '{"target": "tlvstack_vm", "novelty": "throughput"}')
    mut = mutator_factory("havoc", '{"seed": 4}',
                          targets_cgc.tlvstack_vm_seed())
    drv = driver_factory("file", None, instr, mut)
    out = tmp_path / "out"
    fz = Fuzzer(drv, output_dir=str(out), batch_size=64,
                stats_interval=0.0)      # flush every batch
    stats = fz.run(256)

    fs = parse_fuzzer_stats(str(out / "fuzzer_stats"))
    assert int(fs["execs_done"]) == stats.iterations == 256
    assert int(fs["paths_total"]) == stats.new_paths
    assert int(fs["crashes"]) == stats.crashes
    assert int(fs["unique_crashes"]) == stats.unique_crashes
    assert float(fs["execs_per_sec"]) == pytest.approx(
        stats.execs_per_sec, rel=0.05)

    rows = [[float(v) for v in r.split(",")] for r in
            (out / "plot_data").read_text().splitlines()
            if not r.startswith("#")]
    assert len(rows) >= 3                # baseline + >=1 mid + final
    execs_col = [r[1] for r in rows]
    paths_col = [r[2] for r in rows]
    assert execs_col[0] == 0             # baseline row: deltas sum to
    assert execs_col == sorted(execs_col)       # the cumulative total
    assert paths_col == sorted(paths_col)
    assert sum(b - a for a, b in zip(execs_col, execs_col[1:])) \
        == int(fs["execs_done"])
    assert sum(b - a for a, b in zip(paths_col, paths_col[1:])) \
        == int(fs["paths_total"])

    snaps = [json.loads(l) for l in
             (out / "stats.jsonl").read_text().splitlines()]
    assert len(snaps) >= 2
    assert snaps[-1]["counters"]["execs"] == 256
    assert snaps[-1]["derived"]["execs_per_sec_ema"] >= 0
    # stage timers saw the loop's phases without forcing syncs
    assert snaps[-1]["counters"].get("execute_seconds", 0) > 0


def test_cli_no_stats_flag(tmp_path):
    seed_path = tmp_path / "seed"
    seed_path.write_bytes(SEED)
    out = tmp_path / "out"
    rc = cli_main(["file", "jit_harness", "bit_flip",
                   "-i", '{"target": "test"}', "-sf", str(seed_path),
                   "-n", "32", "-o", str(out), "-b", "16",
                   "--no-stats"])
    assert rc == 0
    assert len(os.listdir(out / "crashes")) == 1   # fuzzing unaffected
    for f in ("fuzzer_stats", "plot_data", "stats.jsonl"):
        assert not (out / f).exists()
    # default run DOES write them
    rc = cli_main(["file", "jit_harness", "bit_flip",
                   "-i", '{"target": "test"}', "-sf", str(seed_path),
                   "-n", "32", "-o", str(tmp_path / "out2"), "-b", "16"])
    assert rc == 0
    for f in ("fuzzer_stats", "plot_data", "stats.jsonl"):
        assert (tmp_path / "out2" / f).exists()


def test_cli_inline_mutator_state(tmp_path):
    """Reference -ms parity: mutator state as an inline string (the
    same JSON -msf reads from a file)."""
    seed_path = tmp_path / "seed"
    seed_path.write_bytes(SEED)
    out = tmp_path / "out"
    common = ["file", "jit_harness", "bit_flip", "-i",
              '{"target": "test"}', "-sf", str(seed_path),
              "-o", str(out)]
    rc = cli_main(common + ["-n", "16",
                            "-msd", str(tmp_path / "m.json")])
    assert rc == 0
    state = (tmp_path / "m.json").read_text()
    rc = cli_main(common + ["-n", "16", "-ms", state])
    assert rc == 0
    assert len(os.listdir(out / "crashes")) == 1  # found after resume


MUTATOR_SWEEP = ["bit_flip", "arithmetic", "interesting_value",
                 "havoc", "nop", "ni", "zzuf", "honggfuzz", "afl",
                 "dictionary"]


@pytest.mark.parametrize("mutator", MUTATOR_SWEEP)
@pytest.mark.parametrize("driver", ["file", "stdin"])
def test_mutator_sweep_runs_clean(mutator, driver, tmp_path, capfd):
    """The reference smoke test's mutator sweep (smoke_test.sh:
    204-213): every mutator x {file, stdin} drivers completes a short
    run with nonzero iterations, no exec errors, and no WARNING/ERROR
    log lines (the framework logs to its own stderr stream, so the
    capture is at the fd level; CRITICAL is the legitimate finding
    stream and is allowed)."""
    mopts = None
    if mutator == "dictionary":
        mopts = json.dumps({"tokens": ["ABCD", "zz"]})
    instr = instrumentation_factory("jit_harness",
                                    '{"target": "test"}')
    mut = mutator_factory(mutator, mopts, SEED)
    drv = driver_factory(driver, None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "o"), batch_size=8,
                write_findings=False)
    capfd.readouterr()                      # drop setup noise
    stats = fz.run(16)
    err = capfd.readouterr().err
    assert stats.iterations > 0
    assert stats.errors == 0
    bad = [ln for ln in err.splitlines()
           if " - WARNING - " in ln or " - ERROR - " in ln]
    assert not bad, bad


@pytest.mark.slow  # ~65s interpret-mode pallas sweep (same family as
def test_superbatch_matches_per_batch(tmp_path, monkeypatch):
    # test_fused_cli_path_matches_unfused): nightly lane
    """K-step device-side accumulation (Fuzzer accumulate=K,
    jit_harness._fused_fuzz_multi): candidate/verdict streams and
    on-disk findings must be IDENTICAL to K sequential fused
    batches — same mutator iterations, same PRNG keys, same triage
    fold through the virgin maps."""
    import shutil
    from killerbeez_tpu.models import targets_cgc
    _interpret_pallas(monkeypatch)
    import killerbeez_tpu.instrumentation.jit_harness as jh
    jh._fused_fuzz_multi.clear_cache()
    seed = targets_cgc.tlvstack_vm_seed()

    def run(K, out):
        instr = instrumentation_factory("jit_harness", json.dumps(
            {"target": "tlvstack_vm", "engine": "pallas_fused",
             "novelty": "throughput"}))
        mut = mutator_factory("havoc", '{"seed": 3}', seed)
        drv = driver_factory("file", None, instr, mut)
        fz = Fuzzer(drv, output_dir=str(out), batch_size=512,
                    accumulate=K)
        stats = fz.run(512 * 4)
        return stats, sorted(os.listdir(out / "new_paths")), \
            sorted(os.listdir(out / "crashes"))

    try:
        s1, np1, cr1 = run(1, tmp_path / "k1")
        s2, np2, cr2 = run(2, tmp_path / "k2")
    finally:
        jh._fused_fuzz_multi.clear_cache()
    assert np1 == np2 and cr1 == cr2
    assert (s1.iterations, s1.new_paths, s1.crashes,
            s1.unique_crashes) == \
           (s2.iterations, s2.new_paths, s2.crashes, s2.unique_crashes)
    assert s1.iterations == 2048
