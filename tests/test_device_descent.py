"""Device-resident descent (killerbeez_tpu/search/device_descent.py).

The acceptance contract of the in-scan engine:

  * the operand-capturing distance variant returns the same VMResult
    and distances as the historical path, plus the concrete compare
    operands at the min-distance sample;
  * the stepped mode (scan_iters=1, host drives every iteration) and
    the in-scan mode (scan_iters=R, one dispatch) are BIT-EXACT at
    matched schedules: same elite ranked order, same witness ring —
    the host-vs-device descent parity pin;
  * input-to-state operand matching cracks the planted 4-byte
    magic-compare family (magicsum_vm) in <= 2 dispatches, while the
    probe families alone exhaust at equal budget;
  * every emitted witness is reference-interpreter verified;
  * unconditional edges stand down to the host engine;
  * descent dispatches land on the ``descent`` flight-recorder lane,
    the crack-stage escalation records engine/dispatch metadata, and
    the new telemetry folds through ``aggregate.merge``.
"""

import json

import numpy as np
import pytest

from killerbeez_tpu.analysis.solver import concrete_run, solve_edge
from killerbeez_tpu.models import targets, targets_cgc  # noqa: F401
from killerbeez_tpu.models.compiler import Assembler
from killerbeez_tpu.models.vm import DIST_UNREACHED, run_batch_distances
from killerbeez_tpu.mutators.base import pack_byte_rows
from killerbeez_tpu.search import (
    descend_edge_device, edge_objectives, seeds_reaching_block,
)
from killerbeez_tpu.search.device_descent import (
    FAM_I2S, DeviceDescent,
)


def _never_prog():
    """Impossible eq (a byte can never be 256): exhausts, exercising
    every probe family for as many iterations as asked."""
    a = Assembler("never")
    a.block()                       # 0
    a.ldi(2, 0)
    a.ldb(1, 2)
    a.ldi(2, 1)
    a.alu("mul", 3, 1, 2)
    a.ldi(2, 256)
    a.br("eq", 3, 2, "win")
    a.block()                       # 1
    a.halt(0)
    a.label("win")
    a.block()                       # 2
    a.halt(0)
    return a.build()


def _magicsum():
    return targets.get_target("magicsum_vm")


# --------------------------------------------------------------------
# operand capture (vm.run_batch_distances extension)
# --------------------------------------------------------------------

def test_capture_matches_plain_distances():
    """capture_operands=True returns the same VMResult + distances as
    the historical path, plus the concrete operand values."""
    prog = targets.get_target("imgparse_vm")
    rows = [b"QIMGH\x03\x00\x00\x00\x00\x00", b"QIMG", b"\xff" * 16]
    bufs, lens = pack_byte_rows(rows)
    obj = edge_objectives(prog, (13, 14))[0]
    res0, d0 = run_batch_distances(prog, bufs, lens, (obj.spec(),))
    res1, d1, cx, cy = run_batch_distances(
        prog, bufs, lens, (obj.spec(),), capture_operands=True)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    for f in ("status", "exit_code", "counts", "steps", "path_hash"):
        np.testing.assert_array_equal(np.asarray(getattr(res0, f)),
                                      np.asarray(getattr(res1, f)), f)
    assert np.asarray(cx).shape == (len(rows), 1)


def test_capture_values_are_the_compared_operands():
    """On a byte-vs-constant compare, the captures are exactly the
    loaded byte and the magic constant."""
    a = Assembler("cap_toy")
    a.block()
    a.ldi(2, 0)
    a.ldb(1, 2)
    a.ldi(2, 42)
    a.br("eq", 1, 2, "win")
    a.block()
    a.halt(0)
    a.label("win")
    a.block()
    a.halt(0)
    prog = a.build()
    obj = edge_objectives(prog, (0, 2))[0]
    bufs, lens = pack_byte_rows([bytes([7]), bytes([42]), b""])
    _, d, cx, cy = run_batch_distances(prog, bufs, lens,
                                       (obj.spec(),),
                                       capture_operands=True)
    # the empty lane's LDB reads 0 out-of-bounds: still sampled
    assert np.asarray(cx).ravel().tolist() == [7, 42, 0]
    assert np.asarray(cy).ravel().tolist() == [42, 42, 42]
    assert np.asarray(d).ravel().tolist() == [35.0, 0.0, 42.0]


# --------------------------------------------------------------------
# THE parity pin: stepped (host-driven) vs in-scan at matched schedules
# --------------------------------------------------------------------

def _front_and_witnesses(eng, dispatches):
    wits = []
    for _ in range(dispatches):
        wits.extend(eng.dispatch())
    return eng.elite_front(), wits, eng.witnesses_total, \
        eng.best_primary


@pytest.mark.parametrize("prog_edge_seeds", [
    ("never", (0, 2), [b"\x12\x34\x56"]),
    ("magicsum", (4, 5), [b"\x00" * 6]),
], ids=["toy-exhaust", "magicsum"])
def test_stepped_vs_scanned_bit_exact(prog_edge_seeds):
    """R host-driven single-iteration dispatches == one R-iteration
    scan: same elite ranked order (bufs, lens, stages, distances),
    same witness ring, same best primary distance.  This is the
    host-vs-device descent parity pin: the probe schedule is fully
    deterministic, so where the loop lives must not change WHAT it
    does."""
    name, edge, seeds = prog_edge_seeds
    prog = _never_prog() if name == "never" else _magicsum()
    R = 8
    stepped = DeviceDescent(prog, edge, seeds, lanes=128,
                            scan_iters=1)
    f_step, w_step, t_step, bp_step = _front_and_witnesses(stepped, R)
    scanned = DeviceDescent(prog, edge, seeds, lanes=128,
                            scan_iters=R)
    f_scan, w_scan, t_scan, bp_scan = _front_and_witnesses(scanned, 1)
    for a, b, what in zip(f_step, f_scan,
                          ("bufs", "lens", "stage", "dist")):
        np.testing.assert_array_equal(a, b, f"elite {what}")
    assert w_step == w_scan
    assert t_step == t_scan
    assert bp_step == bp_scan


def test_parity_on_imgparse_frontier():
    """The pin holds on a real CGC-family frontier edge (guard
    curriculum depth > 1, dictionary tokens present)."""
    prog = targets.get_target("imgparse_vm")
    seed = solve_edge(prog, (11, 13)).input
    R = 4
    stepped = DeviceDescent(prog, (14, 15), [seed], lanes=128,
                            scan_iters=1)
    f_step, w_step, _, _ = _front_and_witnesses(stepped, R)
    scanned = DeviceDescent(prog, (14, 15), [seed], lanes=128,
                            scan_iters=R)
    f_scan, w_scan, _, _ = _front_and_witnesses(scanned, 1)
    for a, b, what in zip(f_step, f_scan,
                          ("bufs", "lens", "stage", "dist")):
        np.testing.assert_array_equal(a, b, f"elite {what}")
    assert w_step == w_scan


# --------------------------------------------------------------------
# input-to-state operand matching
# --------------------------------------------------------------------

def test_i2s_cracks_planted_magic_compare_in_2_dispatches():
    """magicsum_vm (4,5): a 32-bit stored-vs-checksum compare the
    solver reports unknown.  Iteration 1 samples the operands,
    iteration 2 writes the observed checksum into the stored field —
    <= 2 dispatches at scan_iters=2, witness verified and tagged
    i2s."""
    prog = _magicsum()
    assert solve_edge(prog, (4, 5)).status == "unknown"
    res = descend_edge_device(prog, (4, 5), [bytes(6)], lanes=128,
                              budget=4, scan_iters=2)
    assert res.status == "descended"
    assert res.dispatches <= 2
    assert res.i2s
    assert res.engine == "device"
    assert (4, 5) in concrete_run(prog, res.input).edges


def test_probe_families_alone_exhaust_at_equal_budget():
    """The ablation behind the bench i2s gate: the same engine with
    i2s lanes disabled cannot crack the 32-bit compare at the same
    iteration budget (coordinate walks need ~30+ iterations to carry
    the descent across four stored bytes)."""
    prog = _magicsum()
    res = descend_edge_device(prog, (4, 5), [bytes(6)], lanes=256,
                              budget=16, scan_iters=8, i2s=False)
    assert res.status == "exhausted"
    on = descend_edge_device(prog, (4, 5), [bytes(6)], lanes=256,
                             budget=16, scan_iters=8, i2s=True)
    assert on.status == "descended" and on.i2s


def test_witness_ring_families_tagged():
    """The witness ring records the generating lane family — the
    telemetry's i2s attribution reads it."""
    prog = _magicsum()
    eng = DeviceDescent(prog, (4, 5), [bytes(6)], lanes=128,
                        scan_iters=4)
    rows = eng.dispatch()
    assert rows, "expected an i2s witness within 4 iterations"
    assert any(fam == FAM_I2S for _, fam, _ in rows)


# --------------------------------------------------------------------
# contracts: honesty, stand-down, flight recorder
# --------------------------------------------------------------------

def test_device_descends_real_frontier_edges():
    """The in-scan engine cracks the same checksum edge the host
    engine owns (imgparse 13:14), faster in dispatch terms, and the
    witness passes the reference interpreter."""
    prog = targets.get_target("imgparse_vm")
    seed = solve_edge(prog, (11, 13)).input
    res = descend_edge_device(prog, (13, 14), [seed], lanes=256,
                              budget=16, scan_iters=8)
    assert res.status == "descended"
    assert res.dispatches <= 2
    assert (13, 14) in concrete_run(prog, res.input).edges


def test_unconditional_edge_stands_down_to_host():
    a = Assembler("uncond")
    a.block()                       # 0
    a.ldi(1, 7)
    a.block()                       # 1 (unconditional successor)
    a.halt(0)
    prog = a.build()
    res = descend_edge_device(prog, (0, 1), [b"\x00"], lanes=64,
                              budget=2, scan_iters=2)
    assert res.engine == "host"
    assert res.status == "descended"    # covering the block covers it


def test_device_spans_on_descent_lane():
    from killerbeez_tpu.telemetry.trace import TraceRecorder
    prog = _never_prog()
    tr = TraceRecorder(max_events=4096)
    descend_edge_device(prog, (0, 2), [b"\x00"], lanes=64, budget=4,
                        scan_iters=2, trace=tr)
    chrome = tr.to_chrome()
    lane_tid = tr.lane_id("descent")
    spans = [e for e in chrome["traceEvents"]
             if e.get("name") == "descend_scan"
             and e.get("tid") == lane_tid and e.get("ph") == "B"]
    assert len(spans) == 2, "one span per device dispatch"
    assert all(s["args"]["scan_iters"] == 2 for s in spans)


def test_budget_is_iteration_denominated():
    """budget=16 at scan_iters=8 is 2 dispatches; the exhausted
    report carries both numbers (the bench denominator)."""
    prog = _never_prog()
    res = descend_edge_device(prog, (0, 2), [b"\x00"], lanes=64,
                              budget=16, scan_iters=8)
    assert res.status == "exhausted"
    assert res.iterations == 16
    assert res.dispatches == 2
    # the engine may round the lane count up to fit the static lane
    # blocks; evals stays iteration-denominated
    assert res.evals % res.iterations == 0
    assert res.evals // res.iterations >= 64


def test_non_multiple_budget_runs_exactly_budget_iterations():
    """The equal-effort contract: a budget scan_iters does not divide
    ends with a shorter TAIL dispatch, never an overshoot — host and
    device comparisons at any budget burn identical iteration
    counts."""
    prog = _never_prog()
    res = descend_edge_device(prog, (0, 2), [b"\x00"], lanes=64,
                              budget=12, scan_iters=8)
    assert res.status == "exhausted"
    assert res.iterations == 12
    assert res.dispatches == 2          # 8 + a 4-iteration tail
    assert res.evals // 12 >= 64 and res.evals % 12 == 0


# --------------------------------------------------------------------
# wiring: cracker escalation, kb-descend report, telemetry folds
# --------------------------------------------------------------------

def test_cracker_device_engine_end_to_end(tmp_path):
    """A blind magicsum campaign with --descend on the device engine:
    the plateau escalates, i2s cracks the compare, the witness
    injects, the cache records the engine/dispatch metadata, and the
    descent gauges/counters are live."""
    from killerbeez_tpu.drivers.factory import driver_factory
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    from killerbeez_tpu.fuzzer.loop import Fuzzer
    from killerbeez_tpu.instrumentation.factory import (
        instrumentation_factory,
    )
    from killerbeez_tpu.mutators.factory import mutator_factory
    instr = instrumentation_factory(
        "jit_harness", json.dumps({"target": "magicsum_vm",
                                   "novelty": "throughput"}))
    mut = mutator_factory("havoc", '{"seed": 11}', b"\x00" * 6)
    drv = driver_factory("file", None, instr, mut)
    fz = Fuzzer(drv, output_dir=str(tmp_path / "out"),
                batch_size=64, write_findings=False)
    fz.cracker = BranchCracker(instr.program, plateau_batches=2,
                               descend=16, descend_lanes=128,
                               descend_engine="device",
                               descend_scan_iters=8)
    fz.run(4096)
    reg = fz.telemetry.registry
    assert reg.counters.get("search_attempts", 0) >= 1
    assert reg.counters.get("search_i2s_matches", 0) >= 1
    assert reg.gauges.get("descent_iterations_per_dispatch") == 8
    entry = fz.cracker.cache.get("4:5")
    assert entry is not None and entry["status"] == "descended"
    assert entry["search"]["engine"] == "device"
    assert entry["search"]["i2s"] is True
    assert entry["search"]["dispatches"] >= 1
    # the injected witness lit the compare edge's slot
    slot = fz.cracker.slot_of_edge[(4, 5)]
    vb = np.asarray(instr.virgin_bits)
    assert int(vb[slot]) != 0xFF


def test_cracker_rejects_bad_engine():
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    with pytest.raises(ValueError):
        BranchCracker(_magicsum(), descend_engine="quantum")


def test_kb_descend_json_round_counts(capsys):
    """kb-descend --json carries per-round dispatch + evaluation
    counts (the bench wall-clock gate's machine-readable
    denominator)."""
    from killerbeez_tpu.tools.descend_tool import main
    rc = main(["magicsum_vm", "--lanes", "128", "--budget", "8",
               "--json", "--edge", "4:5"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["engine"] == "device"
    assert rep["scan_iters"] >= 1
    assert rep["rounds"] and all(
        set(r) >= {"round", "attempted", "cracked", "dispatches",
                   "evals"} for r in rep["rounds"])
    assert rep["dispatches"] >= 1 and rep["evals"] >= 128
    d = rep["edges"]["4:5"]
    assert d["engine"] == "device" and "dispatches" in d


def test_descent_telemetry_folds_through_merge():
    """search_i2s_matches sums and descent_iterations_per_dispatch
    maxes across worker snapshots — the fleet view stays truthful."""
    from killerbeez_tpu.telemetry.aggregate import merge
    a = {"counters": {"search_i2s_matches": 2, "execs": 10},
         "gauges": {"descent_iterations_per_dispatch": 8}}
    b = {"counters": {"search_i2s_matches": 3, "execs": 5},
         "gauges": {"descent_iterations_per_dispatch": 16}}
    m = merge([a, b])
    assert m["counters"]["search_i2s_matches"] == 5
    assert m["gauges"]["descent_iterations_per_dispatch"] == 16


def test_magicsum_crash_reproducer_wins():
    """The registered seed/crash pair holds its contract: the seed
    exits clean, the reproducer traverses the compare edge into the
    planted wild store."""
    from killerbeez_tpu.models.targets_cgc import (
        magicsum_vm_crash, magicsum_vm_seed,
    )
    prog = _magicsum()
    assert (4, 5) not in concrete_run(prog, magicsum_vm_seed()).edges
    assert (4, 5) in concrete_run(prog, magicsum_vm_crash()).edges
