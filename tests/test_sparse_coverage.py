"""Sparse-vs-dense coverage parity: the sparse triage path must give
the same new-path verdicts and the same virgin_bits updates as the
dense bitmap path on arbitrary edge streams."""

import jax.numpy as jnp
import numpy as np

from killerbeez_tpu import MAP_SIZE
from killerbeez_tpu.ops import (
    build_bitmap, classify_counts, has_new_bits_batch, hash_bitmaps,
)
from killerbeez_tpu.ops.sparse_coverage import (
    sparse_classify, sparse_has_new_bits_batch, sparse_simplify,
)


def random_streams(rng, b=16, t=32, n_edges=50):
    """Edge streams with heavy duplication (realistic loops)."""
    pool = rng.integers(0, MAP_SIZE, n_edges)
    ids = pool[rng.integers(0, n_edges, (b, t))].astype(np.int32)
    valid = rng.random((b, t)) < 0.8
    return jnp.asarray(ids), jnp.asarray(valid)


def test_sparse_classify_matches_dense(rng):
    ids, valid = random_streams(rng)
    dense = classify_counts(build_bitmap(ids, valid))
    s_ids, s_cls = sparse_classify(ids, valid)
    s_ids_np, s_cls_np = np.asarray(s_ids), np.asarray(s_cls)
    dense_np = np.asarray(dense)
    for lane in range(ids.shape[0]):
        sparse_map = {}
        for i, c in zip(s_ids_np[lane], s_cls_np[lane]):
            if i < MAP_SIZE:
                sparse_map[int(i)] = int(c)
        dense_map = {int(e): int(dense_np[lane, e])
                     for e in np.flatnonzero(dense_np[lane])}
        assert sparse_map == dense_map, lane


def test_sparse_novelty_matches_dense(rng):
    virgin0 = rng.integers(0, 256, MAP_SIZE).astype(np.uint8)
    virgin0[rng.random(MAP_SIZE) < 0.9] = 0xFF
    for trial in range(3):
        ids, valid = random_streams(rng)
        dense_cls = classify_counts(build_bitmap(ids, valid))
        d_rets, d_virgin = has_new_bits_batch(
            jnp.asarray(virgin0), dense_cls, hash_bitmaps(dense_cls))
        s_ids, s_cls = sparse_classify(ids, valid)
        s_rets, s_virgin = sparse_has_new_bits_batch(
            jnp.asarray(virgin0), s_ids, s_cls)
        np.testing.assert_array_equal(np.asarray(d_rets),
                                      np.asarray(s_rets))
        np.testing.assert_array_equal(np.asarray(d_virgin),
                                      np.asarray(s_virgin))


def test_sparse_dedup_within_batch(rng):
    ids = jnp.asarray(np.array([[7, 9], [7, 9], [9, 7], [3, 3]],
                               dtype=np.int32))
    valid = jnp.ones((4, 2), dtype=bool)
    s_ids, s_cls = sparse_classify(ids, valid)
    virgin = jnp.full((MAP_SIZE,), 0xFF, dtype=jnp.uint8)
    rets, v2 = sparse_has_new_bits_batch(virgin, s_ids, s_cls)
    # lanes 0/1/2 have the identical sorted stream -> only lane 0 new
    assert list(np.asarray(rets)) == [2, 0, 0, 2]
    rets2, _ = sparse_has_new_bits_batch(v2, s_ids, s_cls)
    assert list(np.asarray(rets2)) == [0, 0, 0, 0]


def test_sparse_active_mask(rng):
    ids, valid = random_streams(rng, b=8)
    s_ids, s_cls = sparse_classify(ids, valid)
    simp = sparse_simplify(s_ids)
    virgin = jnp.full((MAP_SIZE,), 0xFF, dtype=jnp.uint8)
    active = jnp.zeros((8,), dtype=bool)
    rets, v2 = sparse_has_new_bits_batch(virgin, s_ids, simp,
                                         active=active)
    assert int(np.asarray(rets).sum()) == 0
    np.testing.assert_array_equal(np.asarray(v2),
                                  np.full(MAP_SIZE, 0xFF, np.uint8))


def test_sparse_count_wrap_matches_dense():
    """An edge hit exactly 256 times wraps to count 0 (class 0) in the
    dense u8 path; the sparse path must agree, not clip to 255."""
    ids = jnp.asarray(np.full((1, 256), 7, dtype=np.int32))
    valid = jnp.ones((1, 256), dtype=bool)
    dense = np.asarray(classify_counts(build_bitmap(ids, valid)))
    assert dense[0, 7] == 0
    s_ids, s_cls = sparse_classify(ids, valid)
    virgin = jnp.full((MAP_SIZE,), 0xFF, dtype=jnp.uint8)
    rets, v2 = sparse_has_new_bits_batch(virgin, s_ids, s_cls)
    assert int(rets[0]) == 0  # wrapped-to-zero edge is invisible
    d_rets, _ = has_new_bits_batch(virgin, jnp.asarray(dense),
                                   hash_bitmaps(jnp.asarray(dense)))
    assert int(d_rets[0]) == int(rets[0])


def test_sparse_empty_stream():
    ids = jnp.full((2, 4), -1, dtype=jnp.int32)
    valid = jnp.zeros((2, 4), dtype=bool)
    s_ids, s_cls = sparse_classify(ids, valid)
    assert (np.asarray(s_ids) == MAP_SIZE).all()
    virgin = jnp.full((MAP_SIZE,), 0xFF, dtype=jnp.uint8)
    rets, v2 = sparse_has_new_bits_batch(virgin, s_ids, s_cls)
    assert list(np.asarray(rets)) == [0, 0]
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(virgin))
