"""Value-set analysis tier tests (analysis/vsa.py + its consumers).

The honesty discipline under test: every abstract domain VSA
publishes must be checkable by concrete replay (``check_replay``),
solver seeding must only ever ADD solved edges (never regress a
verdict), and with no flag passed every consumer surface stays
bit-identical to the pre-VSA behavior — the parity anchor."""

import json

import numpy as np
import pytest

from killerbeez_tpu.analysis import solver as S
from killerbeez_tpu.analysis import vsa as V
from killerbeez_tpu.analysis.cfg import build_cfg
from killerbeez_tpu.analysis.dataflow import (
    _alu_const, _i32, analyze_dataflow,
)
from killerbeez_tpu.analysis.lint import lint_program
from killerbeez_tpu.analysis.priors import (
    PRIOR_SCHEMA, load_priors, save_priors, value_priors,
)
from killerbeez_tpu.grammar.derive import derive_grammar
from killerbeez_tpu.models import targets, targets_cgc  # noqa: F401
from killerbeez_tpu.models.compiler import Assembler


# -- fixture programs ------------------------------------------------

def affine_only_prog():
    """Only fact: (byte[0] + 200) == 300  ->  byte[0] == 100.  The
    literal guarding-constant pass derives nothing (300 > 255)."""
    a = Assembler("affine_only", mem_size=16, max_steps=64)
    a.block()
    a.ldi(1, 0)
    a.ldb(0, 1)
    a.addi(0, 0, 200)
    a.ldi(2, 300)
    a.br("eq", 0, 2, "win")
    a.block()
    a.halt()
    a.label("win")
    a.block()
    a.crash()
    return a.build()


def const_contradiction_prog():
    """No input reads at all; the guard 5 == 9 can never hold, so
    the edge into the crash block is a true, certifiable unsat."""
    a = Assembler("const_contra", mem_size=16, max_steps=64)
    a.block()
    a.ldi(0, 5)
    a.ldi(1, 9)
    a.br("eq", 0, 1, "win")
    a.block()
    a.halt()
    a.label("win")
    a.block()
    a.crash()
    return a.build()


def loop_depth_prog(iters=3):
    """Reaching the tail block requires the loop body to run
    ``iters`` times — a visit-cap unknown at the solver's default
    max_visits=2, solvable once the ladder escalates."""
    a = Assembler("loop_depth", mem_size=16, max_steps=256)
    a.block()
    a.ldi(0, 0)
    a.ldi(1, iters)
    a.label("loop")
    a.block()
    a.addi(0, 0, 1)
    a.br("lt", 0, 1, "loop")
    a.block()
    a.ldi(2, 0)
    a.ldb(3, 2)
    a.ldi(4, 65)
    a.br("eq", 3, 4, "win")
    a.block()
    a.halt()
    a.label("win")
    a.block()
    a.crash()
    return a.build()


def _some_doms():
    VD = V.VDom
    return [
        VD.const(0), VD.const(1), VD.const(-1),
        VD.const(V.INT32_MAX), VD.const(V.INT32_MIN),
        VD.from_vals(frozenset({3, 7, 11})),
        VD.from_vals(frozenset({-5, 0, 5})),
        VD.range(0, 255), VD.range(-8, 8),
        VD(0, 96, 16, None),
        VD.range(V.INT32_MAX - 4, V.INT32_MAX),
    ]


# -- the abstract domains --------------------------------------------

def test_vdom_alu_sound_and_exact_on_small_sets():
    """Every concrete x op y must land inside vdom_alu's result; when
    both inputs enumerate small, the result is exactly the
    elementwise image (the int32-exactness contract)."""
    from killerbeez_tpu.models.vm import (
        ALU_ADD, ALU_AND, ALU_MUL, ALU_OR, ALU_SHL, ALU_SHR, ALU_SUB,
        ALU_XOR,
    )
    sels = (ALU_ADD, ALU_SUB, ALU_AND, ALU_OR, ALU_XOR, ALU_SHL,
            ALU_SHR, ALU_MUL)
    for x in _some_doms():
        for y in _some_doms():
            xs = x.enum(8) or [x.lo, x.hi]
            ys = y.enum(8) or [y.lo, y.hi]
            for sel in sels:
                d = V.vdom_alu(sel, x, y)
                image = {_alu_const(sel, a, b) for a in xs
                         for b in ys}
                for v in image:
                    assert d.contains(v), (sel, x, y, v, d)
                if x.vals is not None and y.vals is not None \
                        and len(x.vals) * len(y.vals) <= 64:
                    assert d.vals == frozenset(
                        _alu_const(sel, a, b)
                        for a in x.vals for b in y.vals), (sel, x, y)


def test_cmp_feasibility_never_refutes_a_witness():
    from killerbeez_tpu.models.vm import (
        CMP_EQ, CMP_GE, CMP_LT, CMP_NE,
    )
    ops = {CMP_EQ: lambda a, b: a == b, CMP_NE: lambda a, b: a != b,
           CMP_LT: lambda a, b: a < b, CMP_GE: lambda a, b: a >= b}
    for x in _some_doms():
        for y in _some_doms():
            xs = x.enum(8) or [x.lo, x.hi]
            ys = y.enum(8) or [y.lo, y.hi]
            for sel, op in ops.items():
                outcomes = {op(a, b) for a in xs for b in ys}
                for want in outcomes:
                    assert V._cmp_feasible(sel, x, y, want), \
                        (sel, x, y, want)


def test_widening_terminates_on_unbounded_loop():
    """A counter with no exit bound must still reach a fixpoint
    (widening), and the widened pc is published honestly."""
    a = Assembler("spin_count", mem_size=16, max_steps=64)
    a.block()
    a.ldi(0, 0)
    a.label("loop")
    a.block()
    a.addi(0, 0, 1)
    a.ldi(1, 0)
    a.ldb(2, 1)
    a.br("eq", 2, 0, "loop")
    a.block()
    a.halt()
    prog = a.build()
    res = V.analyze_vsa(prog)
    assert res.widened_pcs, "loop counter must widen"


# -- replay soundness ------------------------------------------------

REPLAY_TARGETS = ("test", "cgc_like", "imgparse_vm", "tlvstack_vm",
                  "session_auth", "magicsum_vm")
REPLAY_INPUTS = (b"", b"\x00", b"ABCD", b"QI\x10\x04abcdpad",
                 b"\xff" * 24, bytes(range(48)))


@pytest.mark.parametrize("name", REPLAY_TARGETS)
def test_replay_conformance_builtins(name):
    prog = targets.get_target(name)
    vsa = V.analyze_vsa(prog)
    for data in REPLAY_INPUTS:
        assert V.check_replay(prog, data, vsa) == [], (name, data)


def test_check_replay_catches_a_corrupt_domain():
    """The oracle itself must fire: narrow a published domain to
    exclude the actually-executed operand and replay must object."""
    import dataclasses
    prog = targets.get_target("test")
    vsa = V.analyze_vsa(prog)
    data = b"ABCD"
    trace = S.concrete_run(prog, data)
    assert trace.branches
    pc0 = trace.branches[0][0]
    broken = [dataclasses.replace(
        f, x_dom=V.VDom.const(123456), x_affine=None)
        if f.pc == pc0 else f for f in vsa.branches]
    bad = dataclasses.replace(vsa, branches=broken)
    assert V.check_replay(prog, data, bad), \
        "corrupted domain must produce a violation"


# -- document round-trip + store caching -----------------------------

def test_doc_roundtrip_and_stale_rejection():
    prog = targets.get_target("imgparse_vm")
    vsa = V.analyze_vsa(prog)
    doc = vsa.to_doc()
    back = V.VsaResult.from_doc(json.loads(json.dumps(doc)), prog)
    assert back is not None
    assert back.program_sig == vsa.program_sig
    assert len(back.branches) == len(vsa.branches)
    assert [f.as_doc() for f in back.branches] == \
        [f.as_doc() for f in vsa.branches]
    assert back.byte_domains == vsa.byte_domains
    # a different program must reject the doc (stale cache)
    other = targets.get_target("test")
    assert V.VsaResult.from_doc(doc, other) is None
    # schema drift rejects too
    bad = dict(doc, schema="kbz-vsa-v0")
    assert V.VsaResult.from_doc(bad, prog) is None


def test_store_vsa_doc_survives_checkpoint_epochs(tmp_path):
    from killerbeez_tpu.corpus.store import CorpusStore
    prog = targets.get_target("cgc_like")
    vsa = V.analyze_vsa(prog)
    store = CorpusStore(str(tmp_path / "c"))
    store.save_vsa_doc(vsa.to_doc())
    assert V.VsaResult.from_doc(store.load_vsa_doc(),
                                prog) is not None
    # later epochs that do not carry a "vsa" section must not drop it
    store.save_checkpoint({"campaign": {"iterations": 1}})
    store.save_checkpoint({"campaign": {"iterations": 2}})
    doc = store.load_vsa_doc()
    assert doc is not None
    assert V.VsaResult.from_doc(doc, prog) is not None
    # a fresh store process sees it through the checkpoint too
    doc2 = CorpusStore(str(tmp_path / "c")).load_vsa_doc()
    assert doc2 is not None and doc2["program_sig"] == \
        vsa.program_sig


def test_cracker_reuses_cached_vsa_doc(tmp_path):
    from killerbeez_tpu.corpus.store import CorpusStore
    from killerbeez_tpu.fuzzer.crack import BranchCracker
    prog = targets.get_target("cgc_like")
    store = CorpusStore(str(tmp_path / "c"))
    c1 = BranchCracker(prog, store=store, vsa=True)
    r1 = c1._get_vsa()
    assert store.load_vsa_doc() is not None
    c2 = BranchCracker(prog, store=store, vsa=True)
    r2 = c2._get_vsa()
    assert r2.program_sig == r1.program_sig
    assert [f.as_doc() for f in r2.branches] == \
        [f.as_doc() for f in r1.branches]


# -- solver seeding + the escalation ladder --------------------------

def test_seeding_solves_a_baseline_unknown_edge():
    """imgparse_vm: at default budgets VSA seeding must solve at
    least one edge the plain solver reports unknown, never regress
    any verdict, and the new witness must replay through the edge
    (checked here independently of the solver's own verify)."""
    prog = targets.get_target("imgparse_vm")
    vsa = V.analyze_vsa(prog)
    rank = {"solved": 2, "unsat": 1, "unknown": 0}
    uplifted = 0
    for e in sorted(build_cfg(prog).edges):
        b = S.solve_edge(prog, e)
        v = S.solve_edge_vsa(prog, e, vsa=vsa)
        assert rank[v.status] >= rank[b.status], (e, b.status,
                                                  v.status)
        if v.status == "solved" and b.status == "unknown":
            assert e in S.concrete_run(prog, v.input).edges, e
            assert v.vsa is not None
            uplifted += 1
            if uplifted >= 2:
                break
    assert uplifted >= 1, "no baseline-unknown edge was solved"


def test_forced_guard_seeds_are_necessary_conditions():
    """Every seeded byte value set must contain the byte value of
    some input that actually traverses the edge (seeds narrow to
    necessary conditions; a witness must satisfy them)."""
    prog = targets.get_target("imgparse_vm")
    vsa = V.analyze_vsa(prog)
    checked = 0
    for e in sorted(build_cfg(prog).edges):
        r = S.solve_edge(prog, e)
        if r.status != "solved":
            continue
        seeds, _notes = S.vsa_seed_domains(prog, vsa, e)
        for (kind, i), dom in seeds.items():
            assert kind == "byte"
            b = r.input[i] if i < len(r.input) else 0
            if i < len(r.input):
                assert b in dom, (e, i, b, sorted(dom)[:8])
                checked += 1
    assert checked > 0, "no seeded solved edge exercised the check"


def test_unsat_certificate_on_const_contradiction():
    prog = const_contradiction_prog()
    cfg = build_cfg(prog)
    crash_edges = [e for e in cfg.edges if e[1] == 2]
    assert crash_edges
    r = S.solve_edge_vsa(prog, crash_edges[0])
    assert r.status == "unsat"
    cert = r.vsa["certificate"]
    assert cert["exhaustive"] is True
    assert cert["max_visits"] >= 2
    # the baseline agrees (sanity: VSA did not manufacture the unsat)
    assert S.solve_edge(prog, crash_edges[0]).status == "unsat"


def test_visit_ladder_escalates_loop_depth():
    prog = loop_depth_prog(iters=3)
    cfg = build_cfg(prog)
    crash_block = max(b for _, b in cfg.edges)
    edge = [e for e in cfg.edges if e[1] == crash_block][0]
    base = S.solve_edge(prog, edge)     # default max_visits=2
    assert base.status == "unknown"
    assert S.unknown_kind(base.reason) == "visit-cap"
    r = S.solve_edge_vsa(prog, edge)
    assert r.status == "solved"
    assert len(r.vsa["visit_ladder"]) > 1, r.vsa
    assert edge in S.concrete_run(prog, r.input).edges


def test_explain_domains_on_honest_unknown():
    """An edge the ladder cannot settle must name each dependency
    byte's domain — seeded ones with their guard, unseeded ones with
    the honest too-wide verdict."""
    prog = targets.get_target("imgparse_vm")
    vsa = V.analyze_vsa(prog)
    for e in sorted(build_cfg(prog).edges):
        r = S.solve_edge_vsa(prog, e, vsa=vsa)
        if r.status == "unknown":
            doms = r.vsa.get("domains", {})
            assert doms, "unknown verdict must carry domains"
            assert any("seeded" in d or "no dominating" in d
                       for d in doms.values()), doms
            return
    pytest.skip("no unknown edge at default budgets")


# -- grammar + priors consumers --------------------------------------

def test_affine_facts_reach_grammar_and_priors():
    prog = affine_only_prog()
    df = analyze_dataflow(prog)
    vsa = V.analyze_vsa(prog)
    g0 = derive_grammar(prog, df)
    kinds0 = [f.kind for f in g0.rules["msg"].fields]
    assert kinds0 == ["bytes"], "literal pass must derive nothing"
    g1 = derive_grammar(prog, df, vsa=vsa)
    f1 = g1.rules["msg"].fields
    assert f1[0].kind == "lit" and f1[0].value == bytes([100])
    pr = value_priors(prog, vsa, target="affine_only")
    assert pr["schema"] == PRIOR_SCHEMA
    assert pr["positions"]["0"]["values"] == [100]
    assert pr["positions"]["0"]["weights"] == [1]


def test_priors_sidecar_roundtrip(tmp_path):
    prog = targets.get_target("imgparse_vm")
    doc = value_priors(prog, target="imgparse_vm")
    path = tmp_path / "prior.json"
    save_priors(path, doc)
    assert load_priors(path, prog) == doc
    assert load_priors(path, targets.get_target("test")) is None
    path.write_text("{not json")
    assert load_priors(path) is None


# -- lint consumer ---------------------------------------------------

def test_lint_infeasible_edge_severities():
    # constprop agrees (both operands constant) -> error
    p = const_contradiction_prog()
    fs = [f for f in lint_program(p, vsa=V.analyze_vsa(p))
          if f.code == "infeasible-edge"]
    assert [f.severity for f in fs] == ["error"]
    assert fs[0].data["constprop_agrees"] is True

    # VSA-only proof (masked byte vs out-of-range bound) -> warning
    a = Assembler("mask_ge", mem_size=16, max_steps=64)
    a.block()
    a.ldi(1, 0)
    a.ldb(0, 1)
    a.ldi(2, 127)
    a.alu("and", 0, 0, 2)
    a.ldi(3, 200)
    a.br("ge", 0, 3, "win")
    a.block()
    a.halt()
    a.label("win")
    a.block()
    a.crash()
    p2 = a.build()
    fs2 = [f for f in lint_program(p2, vsa=V.analyze_vsa(p2))
           if f.code == "infeasible-edge"]
    assert [f.severity for f in fs2] == ["warning"]
    assert fs2[0].data["constprop_agrees"] is False


def test_lint_value_range_contradiction():
    a = Assembler("contra", mem_size=16, max_steps=64)
    a.block()
    a.ldi(1, 0)
    a.ldb(0, 1)
    a.ldi(2, 3)
    a.br("eq", 0, 2, "g1")
    a.block()
    a.halt()
    a.label("g1")
    a.block()
    a.ldi(3, 7)
    a.br("eq", 0, 3, "g2")
    a.block()
    a.halt()
    a.label("g2")
    a.block()
    a.crash()
    p = a.build()
    fs = [f for f in lint_program(p, vsa=V.analyze_vsa(p))
          if f.code == "value-range-contradiction"]
    assert fs and fs[0].severity == "warning"


def test_lint_guaranteed_oob_store():
    a = Assembler("oob", mem_size=16, max_steps=64)
    a.block()
    a.ldi(1, 0)
    a.ldb(0, 1)
    a.ldi(2, 255)
    a.alu("and", 0, 0, 2)
    a.addi(0, 0, 16)                    # index in [16, 271], mem=16
    a.ldi(3, 7)
    a.stm(0, 3)
    a.halt()
    p = a.build()
    fs = [f for f in lint_program(p, vsa=V.analyze_vsa(p))
          if f.code == "guaranteed-oob-store"]
    assert fs and fs[0].severity == "warning"
    assert fs[0].data["op"] == "stm"


def test_lint_vsa_clean_over_builtins():
    """The CI cleanliness pin: kb-lint --vsa reports ZERO errors on
    every built-in target — stateful targets' forced sides downgrade
    to session-infeasible-edge info, not errors."""
    from killerbeez_tpu.models.targets_stateful import (
        get_stateful_spec,
    )
    for name in targets.target_names():
        prog = targets.get_target(name)
        fs = lint_program(prog, stateful=get_stateful_spec(name),
                          vsa=V.analyze_vsa(prog))
        errs = [f for f in fs if f.severity == "error"]
        assert errs == [], (name, [f.code for f in errs])
        if name in ("session_auth", "tcp_like"):
            assert any(f.code == "session-infeasible-edge"
                       for f in fs), name


# -- the parity anchor -----------------------------------------------

def test_parity_no_flag_surfaces_bit_identical():
    """With no VSA passed anywhere, every consumer output must be
    byte-identical to the pre-VSA behavior."""
    from killerbeez_tpu.models.targets_stateful import (
        get_stateful_spec,
    )
    from killerbeez_tpu.tools.lint_tool import lint_report
    from killerbeez_tpu.tools.solve_tool import solve_report
    vsa_codes = {"infeasible-edge", "session-infeasible-edge",
                 "value-range-contradiction",
                 "session-value-range-contradiction",
                 "guaranteed-oob-store"}
    for name in ("imgparse_vm", "session_auth", "test"):
        prog = targets.get_target(name)
        # solver: no vsa key in any default-path verdict dict
        edges = sorted(build_cfg(prog).edges)[:3]
        rep = solve_report(prog, edges, budget=S.DEFAULT_BUDGET,
                           max_visits=S.DEFAULT_MAX_VISITS,
                           max_len=S.DEFAULT_MAX_LEN, explain=False)
        for d in rep["edges"].values():
            assert "vsa" not in d, name
        # lint: no vsa codes, no vsa section
        fs = lint_program(prog,
                          stateful=get_stateful_spec(name))
        assert not vsa_codes & {f.code for f in fs}, name
        assert "vsa" not in lint_report(prog), name
        # grammar: vsa=None is the identity
        assert derive_grammar(prog) == derive_grammar(prog,
                                                      vsa=None)


def test_kb_lint_json_vsa_section():
    """--json gains a 'vsa' section only under --vsa (satellite:
    mirrors the static stats section discipline)."""
    import contextlib
    import io
    from killerbeez_tpu.tools.lint_tool import main as lint_main
    for flags, want in (([], False), (["--vsa"], True)):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = lint_main(["test", "--json"] + flags)
        assert rc == 0
        rep = json.loads(buf.getvalue())["targets"]["test"]
        assert ("vsa" in rep) is want, flags
        if want:
            assert rep["vsa"]["n_branch_facts"] > 0


def test_kb_solve_vsa_flag_and_explain():
    import contextlib
    import io
    from killerbeez_tpu.tools.solve_tool import main as solve_main
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = solve_main(["imgparse_vm", "--vsa", "--explain",
                         "--block", "2", "--json"])
    assert rc == 0
    rep = json.loads(buf.getvalue())
    assert rep["solved"] >= 1
    assert any("vsa" in d for d in rep["edges"].values())
